# Container recipe for horovod_tpu — role parity with the reference's
# Dockerfile (reference Dockerfile:1-60: CUDA base + TF/PyTorch/Keras +
# OpenMPI + horovod build), reshaped for the TPU stack: no MPI and no
# CUDA anywhere; jax provides the accelerator path and the native TCP
# engine is built from source with plain g++.
#
#   docker build -t horovod-tpu .                 # CPU/CI image
#   docker build --build-arg JAX_VARIANT=tpu -t horovod-tpu .   # TPU VM
#
# Verify the image the same way CI does (8-device virtual CPU mesh — no
# hardware needed):
#
#   docker run --rm horovod-tpu ./ci.sh
#
# On a TPU VM, run with host networking and the TPU runtime mounted as
# that platform documents; multi-host launches use the bundled
# `horovod-tpu-run` console script.

FROM python:3.12-slim-bookworm

RUN apt-get update && apt-get install -y --no-install-recommends \
        build-essential \
    && rm -rf /var/lib/apt/lists/*

WORKDIR /opt/horovod_tpu

# Framework deps first (stable layer, cached across source edits).
# JAX_VARIANT=cpu (default) runs everywhere; =tpu pulls libtpu for TPU
# VMs.  torch is the CPU wheel by design: the torch frontend is a host
# data plane here (accelerator compute is JAX/XLA).
ARG JAX_VARIANT=cpu
RUN pip install --no-cache-dir \
        "jax[${JAX_VARIANT}]" flax optax orbax-checkpoint chex einops \
        ml_dtypes numpy pytest tensorflow-cpu \
    && pip install --no-cache-dir torch \
        --index-url https://download.pytorch.org/whl/cpu

# Source + editable install + native engine build (mirrors ci.sh).
COPY pyproject.toml setup.py README.md ci.sh bench.py bench_engine.py \
     __graft_entry__.py ./
COPY horovod_tpu ./horovod_tpu
COPY tests ./tests
COPY examples ./examples
RUN pip install --no-cache-dir -e . \
    && make -C horovod_tpu/cpp

CMD ["./ci.sh"]
