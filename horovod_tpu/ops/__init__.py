"""Collective ops, compression, and fusion."""

from horovod_tpu.ops.collective_ops import (
    Average,
    Max,
    Min,
    Product,
    ReduceOp,
    Sum,
    allgather,
    allreduce,
    alltoall,
    axis_rank,
    axis_size,
    broadcast,
    grouped_allreduce,
    reducescatter,
)
from horovod_tpu.ops.compression import Compression, Compressor
from horovod_tpu.ops.ragged import (
    bucket_rows,
    compact,
    pad_rows,
    ragged_allgather,
)
from horovod_tpu.ops.fusion import (
    DEFAULT_FUSION_THRESHOLD,
    FusionPlan,
    fuse_apply,
    fusion_threshold_bytes,
    plan_fusion,
)

__all__ = [
    "Average",
    "Max",
    "Min",
    "Product",
    "ReduceOp",
    "Sum",
    "allgather",
    "allreduce",
    "alltoall",
    "axis_rank",
    "axis_size",
    "broadcast",
    "grouped_allreduce",
    "reducescatter",
    "Compression",
    "Compressor",
    "DEFAULT_FUSION_THRESHOLD",
    "FusionPlan",
    "fuse_apply",
    "fusion_threshold_bytes",
    "plan_fusion",
    "bucket_rows",
    "compact",
    "pad_rows",
    "ragged_allgather",
]
