"""Master-weight mixed precision: bf16 compute params, fp32 optimizer.

Why: with fp32-stored params and bf16 compute, XLA inserts a
convert-and-retile of every weight on every step — profiled at ~9% of
the 400M Llama step (docs/perf-notes.md methodology;
`convert_bitcast_fusion` ops).  Storing params in bf16 removes that
traffic (measured 283 -> 267 ms/step, +5.7% tokens/s), but naive bf16
optimizer state loses update precision.  ``master_weights`` keeps the
standard solution: the optimizer state carries an fp32 master copy of
every parameter; gradients are upcast, the inner optimizer's math runs
entirely in fp32 on the master, and the model's bf16 params are re-
derived from the master each step.

Drop-in: wrap any optax ``GradientTransformation`` (including inside
``hvd.DistributedOptimizer``); requires the train step to pass ``params``
to ``update`` (``make_train_step`` does).

Reference note: no equivalent exists in the reference (fp16 there is
wire compression only, `horovod/tensorflow/compression.py`); this is
TPU-era training practice.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax

__all__ = ["master_weights", "cast_compute"]


class MasterWeightsState(NamedTuple):
    master: Any          # fp32 authoritative params
    inner: Any           # wrapped optimizer's state (over the master)


def cast_compute(params, dtype=jnp.bfloat16):
    """Cast a param pytree to the compute dtype (inexact leaves only)."""
    return jax.tree.map(
        lambda p: p.astype(dtype)
        if jnp.issubdtype(p.dtype, jnp.inexact) else p, params)


def master_weights(inner, master_dtype=jnp.float32):
    """Wrap ``inner`` so its math runs on ``master_dtype`` master copies.

    ``init(params)`` snapshots the master from the (typically bf16)
    params; ``update(grads, state, params)`` upcasts grads, steps the
    master, and returns updates that move the compute params to the
    rounded new master (within one ulp of the compute dtype — the master
    remains the authoritative value across steps).
    """

    def init(params):
        master = jax.tree.map(
            lambda p: p.astype(master_dtype)
            if jnp.issubdtype(p.dtype, jnp.inexact) else p, params)
        return MasterWeightsState(master=master, inner=inner.init(master))

    def update(grads, state, params=None, **extra):
        if params is None:
            raise ValueError(
                "master_weights requires params to be passed to update()")
        g_up = jax.tree.map(
            lambda g: g.astype(master_dtype)
            if jnp.issubdtype(g.dtype, jnp.inexact) else g, grads)
        upd, inner_state = inner.update(g_up, state.inner, state.master,
                                        **extra)
        master = optax.apply_updates(state.master, upd)
        # Delta computed in master precision so params + delta lands on
        # the rounded master (drift bounded to 1 compute-dtype ulp and
        # never accumulates: the master is authoritative).
        deltas = jax.tree.map(
            lambda m, p: (m - p.astype(master_dtype)).astype(p.dtype)
            if jnp.issubdtype(p.dtype, jnp.inexact) else jnp.zeros_like(p),
            master, params)
        return deltas, MasterWeightsState(master=master, inner=inner_state)

    return optax.GradientTransformation(init, update)
