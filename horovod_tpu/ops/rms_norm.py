"""Fused RMSNorm as a Pallas TPU kernel (forward + backward).

Why: profiling the Llama train step (docs/perf-notes.md methodology)
shows XLA's RMSNorm-backward fusions running ~13x slower than HBM
bandwidth — the fp32 statistics math over (2,1)-tiled bf16 activations is
VPU/layout-bound, costing ~6% of the step on the 400M bench config.  A
fused kernel does each pass in one read: forward computes the row rstd
and the normalized output together (saving rstd for backward), backward
recomputes x̂ from the saved rstd and produces dx plus a per-rowblock
partial dscale in the same pass.

Measured caveat (why ``LlamaConfig.fused_rmsnorm`` defaults OFF): on the
400M bench config the end-to-end win was only ~0.5% — XLA had already
fused the norms with neighboring converts/residual adds, and the pallas
kernel boundary forfeits that merging.  It remains available for configs
where the norm is a measured bottleneck; benchmark before enabling.

Matches ``models/llama.py:RMSNorm`` math exactly: statistics in fp32,
output cast to the compute dtype, scale applied in fp32.

Layout: x is [R, H] (callers flatten leading dims); H must be a multiple
of 128 and is kept whole in the minor dim (H = 1024-8192 fits VMEM
comfortably at the 256-row blocks used here).  Falls back to plain XLA
math for off-tile shapes or non-TPU backends at equal semantics; tests
pass ``use_kernel=True`` to exercise the kernel logic on CPU via the
Pallas interpreter.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

__all__ = ["rms_norm"]

_BLOCK_R = 256


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _fwd_kernel(x_ref, scale_ref, y_ref, rstd_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)                     # [bR, H]
    rstd = jax.lax.rsqrt(jnp.mean(x * x, axis=1, keepdims=True) + eps)
    y = x * rstd * scale_ref[:].astype(jnp.float32)[None, :]
    y_ref[:] = y.astype(y_ref.dtype)
    # [bR] row statistics, sublane-replicated to the (8, 128) tile.
    rstd_ref[:] = jnp.broadcast_to(rstd.T, (8, x.shape[0]))


def _bwd_kernel(x_ref, scale_ref, rstd_ref, dy_ref, dx_ref, dscale_ref):
    # (eps is not needed here: the derivative is exact through the saved
    # rstd.)
    x = x_ref[:].astype(jnp.float32)
    dy = dy_ref[:].astype(jnp.float32)
    scale = scale_ref[:].astype(jnp.float32)[None, :]
    rstd = rstd_ref[0, :][:, None]                       # [bR, 1]
    xhat = x * rstd
    dys = dy * scale
    # d/dx of mean-square rstd: dx = rstd*(dys - xhat*mean_H(dys*xhat)).
    m = jnp.mean(dys * xhat, axis=1, keepdims=True)
    dx_ref[:] = (rstd * (dys - xhat * m)).astype(dx_ref.dtype)
    # Per-rowblock partial, sublane-replicated to the (8, 128) tile; the
    # caller reads one replica per block.
    part = jnp.sum(dy * xhat, axis=0)
    dscale_ref[:] = jnp.broadcast_to(part[None, :], (8, part.shape[0]))


def _rows_ok(R: int, H: int) -> int:
    for b in (_BLOCK_R, 128, 64, 32, 16, 8):
        if R % b == 0:
            return b
    return 0


def _supported(R: int, H: int) -> bool:
    return H % 128 == 0 and _rows_ok(R, H) > 0


def _reference(x, scale, eps, dtype):
    x32 = x.astype(jnp.float32)
    rstd = jax.lax.rsqrt(jnp.mean(x32 * x32, -1, keepdims=True) + eps)
    return (x32 * rstd * scale.astype(jnp.float32)).astype(dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _rms(x, scale, eps, out_dtype):
    y, _ = _rms_fwd_impl(x, scale, eps, out_dtype)
    return y


def _rms_fwd_impl(x, scale, eps, out_dtype):
    R, H = x.shape
    bR = _rows_ok(R, H)
    y, rstd = pl.pallas_call(
        functools.partial(_fwd_kernel, eps=eps),
        grid=(R // bR,),
        in_specs=[
            pl.BlockSpec((bR, H), lambda i: (i, 0)),
            pl.BlockSpec((H,), lambda i: (0,)),
        ],
        out_specs=[
            pl.BlockSpec((bR, H), lambda i: (i, 0)),
            pl.BlockSpec((8, bR), lambda i: (0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((R, H), out_dtype),
            jax.ShapeDtypeStruct((8, R), jnp.float32),
        ],
        interpret=_interpret(),
    )(x, scale)
    return y, rstd


def _rms_fwd(x, scale, eps, out_dtype):
    y, rstd = _rms_fwd_impl(x, scale, eps, out_dtype)
    return y, (x, scale, rstd)


def _rms_bwd(eps, out_dtype, res, dy):
    x, scale, rstd = res
    R, H = x.shape
    bR = _rows_ok(R, H)
    dx, dscale_parts = pl.pallas_call(
        _bwd_kernel,
        grid=(R // bR,),
        in_specs=[
            pl.BlockSpec((bR, H), lambda i: (i, 0)),
            pl.BlockSpec((H,), lambda i: (0,)),
            pl.BlockSpec((8, bR), lambda i: (0, i)),
            pl.BlockSpec((bR, H), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bR, H), lambda i: (i, 0)),
            pl.BlockSpec((8, H), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((R, H), x.dtype),
            jax.ShapeDtypeStruct((R // bR * 8, H), jnp.float32),
        ],
        interpret=_interpret(),
    )(x, scale, rstd, dy)
    dscale = jnp.sum(
        dscale_parts.reshape(R // bR, 8, H)[:, 0, :], axis=0)
    return dx, dscale.astype(scale.dtype)


_rms.defvjp(_rms_fwd, _rms_bwd)


def rms_norm(x, scale, *, eps: float = 1e-5, out_dtype=None,
             use_kernel: bool | None = None):
    """RMS-normalize ``x`` over its last dim and multiply by ``scale``.

    ``x``: [..., H] (any leading dims); ``scale``: [H].  Statistics in
    fp32; output in ``out_dtype`` (default: ``x.dtype``).  Uses the fused
    Pallas kernel on TPU when H is a multiple of 128; plain XLA math
    (identical semantics) otherwise.  ``use_kernel=True`` forces the
    kernel — off-TPU that means the (slow) Pallas interpreter, which the
    tests use to exercise the kernel logic on CPU."""
    out_dtype = out_dtype or x.dtype
    H = x.shape[-1]
    lead = x.shape[:-1]
    R = 1
    for d in lead:
        R *= d
    if use_kernel is None:
        use_kernel = not _interpret()
    if not use_kernel or not _supported(R, H):
        return _reference(x, scale, eps, out_dtype)
    y = _rms(x.reshape(R, H), scale, eps, out_dtype)
    return y.reshape(*lead, H)
