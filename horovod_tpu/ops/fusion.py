"""Tensor fusion: batching many small tensors into few large collectives.

Reference parity: the Tensor Fusion buffer (``horovod/common/operations.cc``
149-165, 743-767, 1232-1311 and ``docs/tensor-fusion.md``): a 64 MB persistent
buffer per (device, framework); consecutive same-dtype responses are packed
back-to-back, one collective runs over the packed buffer, results are copied
back out.  Threshold via ``HOROVOD_FUSION_THRESHOLD``.

TPU-native design: under XLA there is no persistent staging buffer and no
memcpy — fusion is *flattening the gradient pytree at trace time*.  We
ravel + concatenate same-dtype leaves into flat buffers up to the threshold,
run one ``psum`` per buffer (a single large ICI collective keeps the links
saturated, which is where scaling efficiency is won — SURVEY.md §7 "Fusion on
TPU"), then slice + reshape back.  XLA fuses the pack/unpack copies into the
collective's prologue/epilogue, so unlike the reference there is no extra HBM
round-trip.  The plan is shape-static, so it traces once per pytree structure.
"""

from __future__ import annotations

import os
import sys
from dataclasses import dataclass
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "DEFAULT_FUSION_THRESHOLD",
    "fusion_threshold_bytes",
    "FusionPlan",
    "plan_fusion",
    "fuse_apply",
]

#: 64 MB, matching the reference default (operations.cc:1595).
DEFAULT_FUSION_THRESHOLD = 64 * 1024 * 1024


def fusion_threshold_bytes() -> int:
    """Read ``HOROVOD_FUSION_THRESHOLD`` (bytes), reference knob parity
    (operations.cc:1595-1618).  0 disables fusion."""
    value = os.environ.get("HOROVOD_FUSION_THRESHOLD")
    if value is None or value == "":
        return DEFAULT_FUSION_THRESHOLD
    return int(value)


@dataclass(frozen=True)
class _Bucket:
    dtype: Any
    indices: tuple[int, ...]  # leaf positions in flattened order
    sizes: tuple[int, ...]
    shapes: tuple[tuple[int, ...], ...]


@dataclass(frozen=True)
class FusionPlan:
    buckets: tuple[_Bucket, ...]
    n_leaves: int


def plan_fusion(
    leaves: Sequence[jax.Array], threshold_bytes: int | None = None
) -> FusionPlan:
    """Group leaves into same-dtype buckets of at most ``threshold_bytes``.

    Order within a dtype is preserved; a bucket never mixes dtypes (the
    reference likewise only fuses same-dtype, same-device responses,
    operations.cc:1815-1842).
    """
    if threshold_bytes is None:
        threshold_bytes = fusion_threshold_bytes()
    by_dtype: dict[Any, list[int]] = {}
    for i, leaf in enumerate(leaves):
        by_dtype.setdefault(jnp.asarray(leaf).dtype, []).append(i)

    buckets: list[_Bucket] = []
    for dtype, idxs in by_dtype.items():
        itemsize = np.dtype(dtype).itemsize
        cur: list[int] = []
        cur_bytes = 0
        for i in idxs:
            nbytes = int(np.prod(jnp.shape(leaves[i]), dtype=np.int64)) * itemsize
            if cur and threshold_bytes > 0 and cur_bytes + nbytes > threshold_bytes:
                buckets.append(_mk_bucket(dtype, cur, leaves))
                cur, cur_bytes = [], 0
            if threshold_bytes == 0:
                # Fusion disabled: one leaf per bucket.
                buckets.append(_mk_bucket(dtype, [i], leaves))
                continue
            cur.append(i)
            cur_bytes += nbytes
        if cur:
            buckets.append(_mk_bucket(dtype, cur, leaves))
    return FusionPlan(buckets=tuple(buckets), n_leaves=len(leaves))


def _mk_bucket(dtype, idxs: list[int], leaves) -> _Bucket:
    shapes = tuple(tuple(jnp.shape(leaves[i])) for i in idxs)
    sizes = tuple(int(np.prod(s, dtype=np.int64)) for s in shapes)
    return _Bucket(dtype=dtype, indices=tuple(idxs), sizes=sizes, shapes=shapes)


#: Plans already reported this process (HOROVOD_FUSION_REPORT dedup).
_reported_plans: set = set()


def _maybe_report(plan: FusionPlan) -> None:
    """HOROVOD_FUSION_REPORT=1: print each distinct fusion plan once.

    The jit-path counterpart of the timeline's negotiation visibility
    (SURVEY.md §5.1): fusion happens at TRACE time here, so a one-shot
    bucket report is the observable record of what got batched into each
    ICI collective — the information the eager engine's timeline shows as
    fused response lists."""
    if os.environ.get("HOROVOD_FUSION_REPORT", "0") in ("", "0"):
        return
    key = tuple((str(b.dtype), b.sizes) for b in plan.buckets)
    if key in _reported_plans:
        return
    _reported_plans.add(key)
    print(
        f"horovod_tpu fusion: {plan.n_leaves} tensors -> "
        f"{len(plan.buckets)} fused collective(s)",
        file=sys.stderr,
    )
    for n, b in enumerate(plan.buckets):
        nbytes = sum(b.sizes) * np.dtype(b.dtype).itemsize
        print(
            f"  bucket {n}: {len(b.indices)} x {np.dtype(b.dtype).name}, "
            f"{sum(b.sizes)} elements ({nbytes / 2**20:.2f} MiB)",
            file=sys.stderr,
        )


def fuse_apply(
    tree: Any,
    fn: Callable[[jax.Array], jax.Array],
    threshold_bytes: int | None = None,
) -> Any:
    """Apply ``fn`` (e.g. a psum) over fused flat buffers of ``tree``.

    Equivalent to ``jax.tree.map(fn_elementwise, tree)`` when ``fn`` is an
    elementwise-safe collective, but emits one ``fn`` call per fused bucket
    instead of one per leaf.
    """
    leaves, treedef = jax.tree.flatten(tree)
    if not leaves:
        return tree
    plan = plan_fusion(leaves, threshold_bytes)
    _maybe_report(plan)
    out: list[Any] = [None] * plan.n_leaves
    for bucket in plan.buckets:
        if len(bucket.indices) == 1:
            i = bucket.indices[0]
            out[i] = fn(leaves[i])
            continue
        flat = jnp.concatenate(
            [jnp.ravel(leaves[i]) for i in bucket.indices], axis=0
        )
        reduced = fn(flat)
        offset = 0
        for i, size, shape in zip(bucket.indices, bucket.sizes, bucket.shapes):
            out[i] = jax.lax.slice_in_dim(reduced, offset, offset + size).reshape(shape)
            offset += size
    return jax.tree.unflatten(treedef, out)
