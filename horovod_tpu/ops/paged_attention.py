"""Fused paged-attention decode: attend K/V straight through the block
table — no gather, no contiguous staging.

The serving data path (``horovod_tpu/serve/``) keeps each layer's KV
cache as a pool of fixed-size blocks ``[NB, BS, Hkv, D]`` plus a
per-sequence table of physical block ids.  The oracle decode path
(``models/generation.py::_paged_layer``) gathers every sequence's blocks
back into a contiguous ``[B, MAXB*BS, Hkv, D]`` view before the
attention call — bit-exact against the contiguous cache, but it copies
the whole live cache through HBM on every decode step.  This module is
the vLLM/PagedAttention recipe on that pool: one fused kernel walks the
block table and streams each block through an online softmax, so the
cache is read exactly once and never materialized contiguously.

Decode-step geometry (one query token per sequence): ``q_pos == pos``
and ``k_len == pos + 1`` collapse the oracle's causal+length mask to a
single ``k_pos <= pos`` predicate, which is what both implementations
apply.  Scores and the softmax accumulators are fp32; every row is
computed independently of its batch neighbours, so the output is
deterministic across reruns and invariant to the padded batch width —
the same contract the gather path carries (tests/test_serve.py pins
both).  Unfunded table entries and padded rows point at trash block 0
(a real, finite block), so walking the full table is always safe; fully
masked blocks contribute exactly zero.

Two implementations share that math:

* a Pallas TPU kernel (``grid=(B, MAXB)``) whose pool BlockSpecs index
  through the block table via scalar prefetch
  (``pltpu.PrefetchScalarGridSpec``) — each grid step DMAs exactly one
  physical block into VMEM, the online-softmax state lives in VMEM
  scratch across the table walk;
* a blockwise XLA path (``lax.fori_loop`` over table-column chunks,
  ``HOROVOD_PAGED_ATTN_CHUNK`` columns per online-softmax iteration)
  with the identical masking and fp32 online softmax — the default
  off-TPU, where interpret-mode Pallas inside every jitted decode step
  would dominate the step time.  The chunk default is the whole table
  (one gather + one dense pass: per-block dispatch, not flops, is the
  CPU cost); ``=1`` restores the kernel's exact per-block reduction
  order, which the bitwise-parity suite pins.

``HOROVOD_PAGED_ATTN_IMPL=pallas|xla`` forces one implementation; the
parity suite forces ``pallas`` so CPU CI exercises the actual kernel
logic in interpret mode.  The fused path is numerically equivalent to
the gather oracle, not bitwise: the online softmax re-associates the
reduction over keys.  Observed max |logit| delta on the test corpus is
~1e-6 at fp32 (documented tolerance 1e-4 with argmax stability asserted
on the greedy corpus); ``HOROVOD_SERVE_FUSED_ATTN=0`` keeps the oracle
and is byte-identical to the pre-kernel serve plane.
"""

from __future__ import annotations

import os
import threading
from typing import Dict

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["paged_attention_decode"]

_NEG_INF = -1e30  # matches ops/flash_attention.py (never -inf on TPU)

_fallbacks: Dict[str, int] = {}
_fallback_lock = threading.Lock()


def _note_fallback(key: str, msg: str) -> None:
    """Warn once per reason, count always (mirrors flash_attention)."""
    with _fallback_lock:
        first = key not in _fallbacks
        _fallbacks[key] = _fallbacks.get(key, 0) + 1
    if first:
        import warnings

        warnings.warn(f"paged_attention: {msg}", RuntimeWarning,
                      stacklevel=3)


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _impl() -> str:
    forced = os.environ.get("HOROVOD_PAGED_ATTN_IMPL", "").strip().lower()
    if forced in ("pallas", "xla"):
        return forced
    return "pallas" if jax.default_backend() == "tpu" else "xla"


# ---------------------------------------------------------------------------
# Blockwise XLA implementation (off-TPU default; same math as the kernel)
# ---------------------------------------------------------------------------


def _chunk_cols(maxb: int) -> int:
    """Table columns folded into one online-softmax iteration.

    The loop body's per-iteration cost off-TPU is dominated by dispatch
    (a tiny gather + tiny einsums per block), not flops, so the default
    folds the WHOLE table into a single pass — one gather, one dense
    masked softmax, oracle-speed on CPU where this path is only the
    stand-in for the Pallas kernel.  ``HOROVOD_PAGED_ATTN_CHUNK=1``
    restores the per-block walk whose reduction order matches the TPU
    kernel exactly (the bitwise-parity suite pins it).  Read at trace
    time: the engine's per-batch-width jit caches each bake the value
    in effect at first trace.
    """
    raw = os.environ.get("HOROVOD_PAGED_ATTN_CHUNK", "").strip()
    if not raw:
        return maxb
    return max(1, min(int(raw), maxb))


def _decode_blockwise(q, pool_k, pool_v, tables, pos):
    """Online-softmax walk over table-column chunks without contiguous
    staging.

    q: [B, 1, Hq, D]; pool_k/pool_v: [NB, BS, Hkv, D];
    tables: [B, MAXB] int32; pos: [B].  Returns [B, 1, Hq, D].
    """
    B, _, Hq, D = q.shape
    BS, Hkv = pool_k.shape[1], pool_k.shape[2]
    G = Hq // Hkv
    maxb = tables.shape[1]
    C = _chunk_cols(maxb)
    nchunks = -(-maxb // C)
    if nchunks * C != maxb:
        # Pad ragged tails with trash block 0: real memory, and every
        # padded column's k_pos >= MAXB*BS > pos, so the mask kills it.
        tables = jnp.concatenate(
            [tables, jnp.zeros((B, nchunks * C - maxb), tables.dtype)],
            axis=1)
    scale = 1.0 / jnp.sqrt(jnp.asarray(D, jnp.float32))
    qg = q.reshape(B, Hkv, G, D)

    def body(j, carry):
        m, l, acc = carry
        bids = jax.lax.dynamic_slice_in_dim(tables, j * C, C, axis=1)
        kb = pool_k[bids].reshape(B, C * BS, Hkv, D)
        vb = pool_v[bids].reshape(B, C * BS, Hkv, D)
        s = jnp.einsum("bhgd,bkhd->bhgk", qg, kb,
                       preferred_element_type=jnp.float32) * scale
        k_pos = j * (C * BS) + jnp.arange(C * BS)
        live = k_pos[None, :] <= pos[:, None]       # [B, C*BS]
        s = jnp.where(live[:, None, None, :], s, _NEG_INF)
        new_m = jnp.maximum(m, jnp.max(s, axis=-1))
        alpha = jnp.exp(m - new_m)
        p = jnp.exp(s - new_m[..., None])
        l = l * alpha + jnp.sum(p, axis=-1)
        acc = acc * alpha[..., None] + jnp.einsum(
            "bhgk,bkhd->bhgd", p.astype(vb.dtype), vb,
            preferred_element_type=jnp.float32)
        return new_m, l, acc

    m0 = jnp.full((B, Hkv, G), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, Hkv, G), jnp.float32)
    a0 = jnp.zeros((B, Hkv, G, D), jnp.float32)
    # Block 0 always holds the row's position-0 slot, so l > 0 for every
    # row (padded rows attend one trash slot; their output is discarded).
    m, l, acc = jax.lax.fori_loop(0, nchunks, body, (m0, l0, a0))
    out = (acc / l[..., None]).astype(q.dtype)
    return out.reshape(B, 1, Hq, D)


# ---------------------------------------------------------------------------
# Pallas TPU kernel: the block table rides scalar prefetch, so each grid
# step's BlockSpec index map picks the PHYSICAL block to DMA — the fused
# "no gather" read path.
# ---------------------------------------------------------------------------


def _decode_kernel(tables_ref, pos_ref, q_ref, k_ref, v_ref, o_ref,
                   m_ref, l_ref, acc_ref, *, block_size):
    b = pl.program_id(0)
    j = pl.program_id(1)
    nblk = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    p0 = pos_ref[b]

    # Blocks wholly beyond the row's live length are fully masked —
    # skip their flops (their DMA already happened; the table points
    # unfunded entries at trash block 0, a real block, so it is safe).
    @pl.when(j * block_size <= p0)
    def _accumulate():
        Hq, D = q_ref.shape
        BS, Hkv, _ = k_ref.shape
        G = Hq // Hkv
        qg = q_ref[...].reshape(Hkv, G, D)
        k = k_ref[...]                              # [BS, Hkv, D]
        s = jax.lax.dot_general(
            qg, k, (((2,), (2,)), ((0,), (1,))),
            preferred_element_type=jnp.float32)     # [Hkv, G, BS]
        s = s * (1.0 / jnp.sqrt(jnp.asarray(D, jnp.float32)))
        k_pos = jax.lax.broadcasted_iota(jnp.int32, (Hkv, G, BS), 2) \
            + j * block_size
        s = jnp.where(k_pos <= p0, s, _NEG_INF)
        m = m_ref[...]
        new_m = jnp.maximum(m, jnp.max(s, axis=-1))
        alpha = jnp.exp(m - new_m)
        p = jnp.exp(s - new_m[..., None])
        m_ref[...] = new_m
        l_ref[...] = l_ref[...] * alpha + jnp.sum(p, axis=-1)
        acc_ref[...] = acc_ref[...] * alpha[..., None] + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[...],
            (((2,), (0,)), ((0,), (1,))),
            preferred_element_type=jnp.float32)     # [Hkv, G, D]

    @pl.when(j == nblk - 1)
    def _finish():
        out = acc_ref[...] / l_ref[...][..., None]
        o_ref[...] = out.reshape(o_ref.shape).astype(o_ref.dtype)


def _decode_pallas(q, pool_k, pool_v, tables, pos):
    B, _, Hq, D = q.shape
    BS, Hkv = pool_k.shape[1], pool_k.shape[2]
    G = Hq // Hkv
    maxb = tables.shape[1]
    import functools

    kernel = functools.partial(_decode_kernel, block_size=BS)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, maxb),
        in_specs=[
            pl.BlockSpec((None, Hq, D),
                         lambda b, j, tables, pos: (b, 0, 0)),
            pl.BlockSpec((None, BS, Hkv, D),
                         lambda b, j, tables, pos: (tables[b, j], 0, 0, 0)),
            pl.BlockSpec((None, BS, Hkv, D),
                         lambda b, j, tables, pos: (tables[b, j], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, Hq, D),
                               lambda b, j, tables, pos: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((Hkv, G), jnp.float32),
            pltpu.VMEM((Hkv, G), jnp.float32),
            pltpu.VMEM((Hkv, G, D), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Hq, D), q.dtype),
        interpret=_interpret(),
    )(tables.astype(jnp.int32), pos.astype(jnp.int32),
      q.reshape(B, Hq, D), pool_k, pool_v)
    return out.reshape(B, 1, Hq, D)


def paged_attention_decode(q, pool_k, pool_v, tables, pos):
    """Fused paged-attention for one decode step.

    q: [B, 1, Hq, D] query (this step's token, post-RoPE); pool_k/pool_v:
    one layer's pool [NB, BS, Hkv, D] with the step's K/V already written
    at each row's ``pos`` slot; tables: [B, MAXB] int32 physical block
    ids; pos: [B] global position per row.  Returns [B, 1, Hq, D] in
    ``q.dtype`` — the drop-in replacement for the gather +
    ``_attend_b(..., q_pos=pos, k_len=pos+1)`` pair in
    ``models/generation.py::_paged_layer``.
    """
    if _impl() == "pallas":
        try:
            return _decode_pallas(q, pool_k, pool_v, tables, pos)
        except Exception as e:  # pragma: no cover - backend specific
            _note_fallback(
                "pallas", f"pallas paged decode failed ({type(e).__name__}: "
                f"{e}); using the blockwise XLA path")
    return _decode_blockwise(q, pool_k, pool_v, tables, pos)
