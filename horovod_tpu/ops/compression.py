"""Gradient compression algorithms.

Reference parity: ``horovod/tensorflow/compression.py`` and
``horovod/torch/compression.py`` (both 74 LoC): a ``Compressor`` interface
with ``none`` and ``fp16`` members of a ``Compression`` registry; compress
casts floats down, decompress casts back.

TPU-native note: bfloat16 is the TPU's native reduced-precision format — it
shares float32's exponent range so gradient allreduce in bf16 is far safer
than fp16 (no overflow rescaling needed) and feeds the MXU directly.  We keep
``fp16`` for API parity and add ``bf16`` as the recommended member.
"""

from __future__ import annotations

import jax.numpy as jnp

__all__ = ["Compressor", "NoneCompressor", "FP16Compressor", "BF16Compressor", "Compression"]


class Compressor:
    """Interface for compressing and decompressing a tensor."""

    @staticmethod
    def compress(tensor):
        """Returns (compressed_tensor, context) for decompress."""
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        """Returns the decompressed tensor."""
        raise NotImplementedError


class NoneCompressor(Compressor):
    """Default no-op compression."""

    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class _CastCompressor(Compressor):
    wire_dtype: jnp.dtype

    @classmethod
    def compress(cls, tensor):
        dtype = tensor.dtype
        if jnp.issubdtype(dtype, jnp.floating) and dtype != cls.wire_dtype:
            return tensor.astype(cls.wire_dtype), dtype
        return tensor, None

    @classmethod
    def decompress(cls, tensor, ctx):
        if ctx is not None:
            return tensor.astype(ctx)
        return tensor


class FP16Compressor(_CastCompressor):
    """Cast floating-point gradients to float16 on the wire."""

    wire_dtype = jnp.float16


class BF16Compressor(_CastCompressor):
    """Cast floating-point gradients to bfloat16 on the wire (TPU-native)."""

    wire_dtype = jnp.bfloat16


class Compression:
    """Registry of compression algorithms (reference compression.py:67-74)."""

    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
