"""Gradient compression algorithms.

Reference parity: ``horovod/tensorflow/compression.py`` and
``horovod/torch/compression.py`` (both 74 LoC): a ``Compressor`` interface
with ``none`` and ``fp16`` members of a ``Compression`` registry; compress
casts floats down, decompress casts back.

TPU-native note: bfloat16 is the TPU's native reduced-precision format — it
shares float32's exponent range so gradient allreduce in bf16 is far safer
than fp16 (no overflow rescaling needed) and feeds the MXU directly.  We keep
``fp16`` for API parity and add ``bf16`` as the recommended member.
"""

from __future__ import annotations

import jax.numpy as jnp

__all__ = ["Compressor", "NoneCompressor", "FP16Compressor",
           "BF16Compressor", "WireCompressor", "TopKCompressor",
           "Compression"]


class Compressor:
    """Interface for compressing and decompressing a tensor."""

    @staticmethod
    def compress(tensor):
        """Returns (compressed_tensor, context) for decompress."""
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        """Returns the decompressed tensor."""
        raise NotImplementedError


class NoneCompressor(Compressor):
    """Default no-op compression."""

    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class _CastCompressor(Compressor):
    wire_dtype: jnp.dtype

    @classmethod
    def compress(cls, tensor):
        dtype = tensor.dtype
        if jnp.issubdtype(dtype, jnp.floating) and dtype != cls.wire_dtype:
            return tensor.astype(cls.wire_dtype), dtype
        return tensor, None

    @classmethod
    def decompress(cls, tensor, ctx):
        if ctx is not None:
            return tensor.astype(ctx)
        return tensor


class FP16Compressor(_CastCompressor):
    """Cast floating-point gradients to float16 on the wire."""

    wire_dtype = jnp.float16


class BF16Compressor(_CastCompressor):
    """Cast floating-point gradients to bfloat16 on the wire (TPU-native)."""

    wire_dtype = jnp.bfloat16


class WireCompressor(Compressor):
    """WIRE-level compression: the tensor stays fp32 end to end in user
    code (compress/decompress are identities); the native engine
    quantizes on send and dequantizes-reduces-requantizes on the ring
    with per-chunk scales (``HOROVOD_WIRE_DTYPE`` semantics, negotiated
    and validated cross-rank).  Host/eager collectives only — inside
    jit the collective is an XLA op and this degrades to a no-op."""

    engine_wire_dtype: str = "fp32"

    @classmethod
    def compress(cls, tensor):
        return tensor, None

    @classmethod
    def decompress(cls, tensor, ctx):
        return tensor


class _WireFP16(WireCompressor):
    engine_wire_dtype = "fp16"


class _WireBF16(WireCompressor):
    engine_wire_dtype = "bf16"


class _WireInt8(WireCompressor):
    engine_wire_dtype = "int8"


class _WireFP8(WireCompressor):
    engine_wire_dtype = "fp8"


class TopKCompressor:
    """Top-k sparse allreduce spec with error-feedback residuals (Deep
    Gradient Compression, Lin et al. 2018).  NOT a cast compressor: the
    eager allreduce path recognizes instances and routes the collective
    through :func:`horovod_tpu.runtime.sparse.sparse_allreduce_topk`,
    which keeps one residual buffer per tensor NAME (i.e. per gradient
    leaf) and clears it per membership epoch.  Host/eager collectives
    only — inside jit the collective is an XLA op and this degrades to a
    dense allreduce."""

    def __init__(self, ratio=None, error_feedback: bool = True):
        # None defers to the HOROVOD_SPARSE_TOPK env default (resolved
        # per call by sparse_allreduce_topk) — the documented knob.
        if ratio is not None and not 0.0 < ratio <= 1.0:
            raise ValueError(f"topk ratio must be in (0, 1], got {ratio}")
        self.ratio = float(ratio) if ratio is not None else None
        self.error_feedback = bool(error_feedback)

    # Identity compress/decompress so code that treats every member of
    # the registry as a cast compressor (the traced/jit path) still
    # composes — it just gets the dense collective.
    def compress(self, tensor):
        return tensor, None

    def decompress(self, tensor, ctx):
        return tensor


class Compression:
    """Registry of compression algorithms (reference compression.py:67-74).

    ``none``/``fp16``/``bf16`` are the reference's FRONTEND casts (the
    tensor itself changes dtype).  ``wire_fp16``/``wire_bf16``/
    ``wire_int8``/``wire_fp8`` compress at the WIRE level instead — the
    engine carries quantized bytes with per-chunk scales and hands back
    fp32 — and ``topk(ratio)`` builds a sparse top-k spec with
    error-feedback residuals per gradient leaf."""

    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
    wire_fp16 = _WireFP16
    wire_bf16 = _WireBF16
    wire_int8 = _WireInt8
    wire_fp8 = _WireFP8

    @staticmethod
    def topk(ratio=None, error_feedback: bool = True) -> TopKCompressor:
        """``ratio=None`` defers to HOROVOD_SPARSE_TOPK (default 0.01)."""
        return TopKCompressor(ratio, error_feedback)
