"""SPMD collective primitives over named mesh axes.

Reference parity: the three collectives of the reference core —
``EnqueueTensorAllreduce/Allgather/Broadcast`` (``horovod/common/
operations.h:100-118``) executed as ``MPI_Allreduce`` / ``MPI_Allgatherv`` /
``MPI_Bcast`` or their NCCL twins (``operations.cc:714-1362``).

TPU-native design: inside ``jit``-compiled SPMD programs there is no enqueue,
no negotiation and no fusion buffer — the program *is* identical on every
device by construction, so collectives are single XLA ops over a named mesh
axis, lowered directly to ICI rings (``psum``/``all_gather``/``ppermute``).
These functions are the building blocks; the eager, named-tensor negotiation
engine (for the torch frontend and host-driven code) lives in
``horovod_tpu.runtime`` and ultimately executes *these same ops*.

The ``broadcast`` trick: XLA has no bcast collective; ``psum`` of a tensor
masked to zero on all non-root shards is mathematically a broadcast and
lowers to the same ring reduction, which is optimal on ICI.
"""

from __future__ import annotations

import enum
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

import horovod_tpu.common.jax_compat  # noqa: F401  (lax.axis_size shim)

from horovod_tpu.ops.compression import Compression

__all__ = [
    "ReduceOp",
    "Sum",
    "Average",
    "Min",
    "Max",
    "Product",
    "allreduce",
    "grouped_allreduce",
    "allgather",
    "broadcast",
    "reducescatter",
    "alltoall",
    "axis_rank",
    "axis_size",
]


class ReduceOp(enum.Enum):
    """Reduction ops.  The reference wire protocol supports allreduce-sum
    only, with averaging applied by the framework layer
    (``horovod/torch/mpi_ops_v2.cc:66-72``); later Horovods named these.
    """

    SUM = "sum"
    AVERAGE = "average"
    MIN = "min"
    MAX = "max"
    PRODUCT = "product"


Sum = ReduceOp.SUM
Average = ReduceOp.AVERAGE
Min = ReduceOp.MIN
Max = ReduceOp.MAX
Product = ReduceOp.PRODUCT


def axis_rank(axis_name) -> jax.Array:
    """This shard's index along ``axis_name`` (in-jit)."""
    return lax.axis_index(axis_name)


def axis_size(axis_name) -> int:
    return lax.axis_size(axis_name)


def _reduce(tensor: jax.Array, axis_name, op: ReduceOp) -> jax.Array:
    if op is ReduceOp.SUM:
        return lax.psum(tensor, axis_name)
    if op is ReduceOp.AVERAGE:
        return lax.pmean(tensor, axis_name)
    if op is ReduceOp.MIN:
        return lax.pmin(tensor, axis_name)
    if op is ReduceOp.MAX:
        return lax.pmax(tensor, axis_name)
    if op is ReduceOp.PRODUCT:
        # XLA has no product collective; gather-then-multiply is exact for
        # every dtype (a log/exp trick would lose integer exactness).
        gathered = lax.all_gather(tensor, axis_name, axis=0, tiled=False)
        return jnp.prod(gathered, axis=0)
    raise ValueError(f"unknown op {op}")


def allreduce(
    tensor: jax.Array,
    *,
    axis_name="data",
    op: ReduceOp = Average,
    compression=Compression.none,
    average: Optional[bool] = None,
) -> jax.Array:
    """Allreduce ``tensor`` over mesh axis ``axis_name``.

    ``average`` kwarg keeps the reference signature
    (``horovod/tensorflow/__init__.py:44-87``); ``compression`` casts to the
    wire dtype for the reduction only.
    """
    if average is not None:
        op = Average if average else Sum
    wire, ctx = compression.compress(tensor)
    reduced = _reduce(wire, axis_name, op)
    return compression.decompress(reduced, ctx)


def grouped_allreduce(
    tensors: Sequence[jax.Array],
    *,
    axis_name="data",
    op: ReduceOp = Average,
    compression=Compression.none,
) -> list[jax.Array]:
    """Allreduce a list of tensors as one fused collective per dtype.

    Reference parity: response fusion (operations.cc:1815-1842).  Uses the
    trace-time fusion planner, so many small gradients become one large ICI
    ring transfer.
    """
    from horovod_tpu.ops.fusion import fuse_apply

    def _fn(buf):
        return allreduce(buf, axis_name=axis_name, op=op, compression=compression)

    return fuse_apply(list(tensors), _fn)


def allgather(
    tensor: jax.Array, *, axis_name="data", axis: int = 0
) -> jax.Array:
    """Concatenate each shard's ``tensor`` along ``axis`` (dim 0 by default),
    matching reference allgather semantics (operations.cc:796-856).

    XLA requires static shapes, so unlike the reference the per-shard dim-0
    sizes must be equal inside jit; ragged gathers are handled by the eager
    engine via pad-to-max (SURVEY.md §3.5).
    """
    return lax.all_gather(tensor, axis_name, axis=axis, tiled=True)


def broadcast(
    tensor: jax.Array, root_rank: int = 0, *, axis_name="data"
) -> jax.Array:
    """Every shard receives root's value (reference operations.cc:1333-1353)."""
    idx = lax.axis_index(axis_name)
    masked = jnp.where(idx == root_rank, tensor, jnp.zeros_like(tensor))
    if jnp.issubdtype(tensor.dtype, jnp.inexact) or jnp.issubdtype(
        tensor.dtype, jnp.integer
    ):
        return lax.psum(masked, axis_name)
    raise TypeError(f"broadcast: unsupported dtype {tensor.dtype}")


def reducescatter(
    tensor: jax.Array,
    *,
    axis_name="data",
    op: ReduceOp = Sum,
    scatter_axis: int = 0,
    tiled: bool = True,
) -> jax.Array:
    """Reduce then scatter shards along ``scatter_axis``.

    Not in the 0.15.1 API, but it is the first half of the reference's
    hierarchical allreduce (ncclReduceScatter, operations.cc:1025-1187) and
    the core primitive of the FSDP layer.
    """
    out = lax.psum_scatter(
        tensor, axis_name, scatter_dimension=scatter_axis, tiled=tiled
    )
    if op is Average:
        out = out / lax.axis_size(axis_name)
    return out


def alltoall(
    tensor: jax.Array,
    *,
    axis_name="seq",
    split_axis: int = 0,
    concat_axis: int = 0,
) -> jax.Array:
    """All-to-all over a mesh axis (Ulysses-style sequence parallelism
    building block; no reference equivalent — TPU-native extension)."""
    return lax.all_to_all(
        tensor, axis_name, split_axis=split_axis, concat_axis=concat_axis,
        tiled=True,
    )
