"""Flash attention as a Pallas TPU kernel.

No reference equivalent (the reference has no attention at all, SURVEY.md
§5.7); this is the framework's hot-op kernel for transformer training
(/opt/skills/guides/pallas_guide.md is the API playbook).

Design (FlashAttention-2 style, causal):
* forward: grid over (batch*heads, query blocks); K/V live in VMEM for
  the whole row of the grid; online softmax (running max + normalizer)
  in fp32 scratch, so the [S, S] score matrix never exists and HBM
  traffic is O(S·D) instead of O(S²);
* backward: two kernels — dQ (grid over query blocks, loop over KV
  blocks) and dK/dV (grid over KV blocks, loop over query blocks) — both
  recompute probabilities from the saved log-sum-exp, the standard
  FLOPs-for-memory trade;
* fp32 accumulation on the MXU via ``preferred_element_type``; bf16 in /
  bf16 out;
* causal masking is block-aware: KV blocks entirely above the diagonal
  are skipped (the loop bound, not a mask), the diagonal block gets the
  intra-block triangle.

``flash_attention`` is a drop-in for the model zoo's ``attention_fn``
seam ([B, S, H, D] layout, GQA via KV-head repetition).  Shapes off the
kernel's tiling are zero-padded onto it (sequence to the next 128,
head dim to the next 64 with the softmax scale folded into q) and
sliced back, so models keep the kernel — and its O(S) memory contract —
unchanged on any shape; ``interpret=True`` is used automatically
off-TPU so tests exercise the same kernel logic on CPU.

Measured on one v5e (bf16, B=4 H=16 D=128, vs XLA's fused dense
attention): S=4096 1.8x faster (31 TF/s), S=8192 3.2x (66 TF/s, ~59% of
the chip's 112 TF/s matmul peak); fwd+bwd 1.9x at S=4096.  Crossover is
around S≈2048 — below that XLA's dense fusion wins on latency (flash
still wins on memory).
"""

from __future__ import annotations

import functools
import math
import threading
import warnings
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

__all__ = ["flash_attention", "flash_attention_fn", "flash_attention_lse",
           "flash_lse_supported", "fallback_count"]

# Non-kernel-path observability: a production config losing a Pallas
# kernel should not do so silently.  flash_attention itself pads any
# shape to the kernel, so the counter tracks COMPOSING callers choosing
# a non-kernel implementation (e.g. ring attention's XLA online-softmax
# hop when the strict lse kernel's tiling is off).  Each distinct reason
# warns once per process; the counter counts every fallback TRACE (not
# execution — under jit the choice is made at trace time).  Guarded by a
# lock: jax tracing can run on multiple threads.
_fallbacks: dict = {}
_fallbacks_lock = threading.Lock()


def fallback_count() -> int:
    """Number of times a composing caller chose a non-kernel attention
    path at trace time (``flash_attention`` itself always pads onto the
    kernel; e.g. ring attention's XLA online-softmax hop counts here),
    summed over every reason and call site in this process (the counter
    is process-global, incremented once per traced fallback, not per
    kernel execution)."""
    with _fallbacks_lock:
        return sum(_fallbacks.values())


def _note_fallback(reason: str) -> None:
    with _fallbacks_lock:
        first = reason not in _fallbacks
        _fallbacks[reason] = _fallbacks.get(reason, 0) + 1
    if first:
        warnings.warn("flash kernel not used: " + reason,
                      RuntimeWarning, stacklevel=3)

_NEG_INF = float("-inf")

BLOCK_Q = 512     # upper bounds; shrunk to the largest divisor of S
BLOCK_K = 512


def _pick_block(s: int, cap: int) -> int:
    for b in (cap, 256, 128):
        if b <= cap and s % b == 0:
            return b
    return 0


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _seg_mask(scores, seg_start, ki, block_k):
    """Mask keys below each query's segment start (packed causal
    attention); shared by the forward and both backward kernels."""
    block_q = scores.shape[0]
    k_pos = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    return jnp.where(k_pos >= seg_start[:, None], scores, -1e30)


# ---------------------------------------------------------------------------
# Forward kernel
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, causal, sm_scale,
                block_k, bias_ref=None, seg_ref=None):
    # q_ref: [block_q, D]; k_ref/v_ref: [S, D]; o_ref: [block_q, D];
    # bias_ref (optional): [8, S] additive key bias (0 valid / -1e30
    # masked), sublane-replicated like lse — key-padding masks for
    # bidirectional (BERT-style) attention.
    # seg_ref (optional, causal only): [8, S] int32 — per-position START of
    # the position's segment; queries only attend keys at positions
    # >= their segment start.  With the causal upper bound this yields
    # block-diagonal attention for PACKED sequences (row i attends
    # [seg_start[i], i]) without a [S, S] mask.
    qi = pl.program_id(1)
    block_q, d = q_ref.shape
    s = k_ref.shape[0]
    q = q_ref[:]
    seg_start = None
    if seg_ref is not None:
        seg_start = seg_ref[0, pl.dslice(qi * block_q, block_q)]

    m = jnp.full((block_q,), -1e30, jnp.float32)
    l = jnp.zeros((block_q,), jnp.float32)
    acc = jnp.zeros((block_q, d), jnp.float32)

    n_kv = s // block_k
    if causal:
        # Query block qi covers rows [qi*bq, (qi+1)*bq); KV blocks fully
        # above the diagonal contribute nothing — bound the loop instead
        # of masking.
        # ceil((qi+1)*bq / bk): every KV block touching or below the
        # diagonal, valid for ANY bq/bk ratio (bq < bk included).
        n_kv_live = jnp.minimum(
            ((qi + 1) * block_q + block_k - 1) // block_k, n_kv)
    else:
        n_kv_live = n_kv
    kv_first = 0
    if seg_start is not None:
        # Packed rows: KV blocks wholly before this query block's earliest
        # segment start are 100% masked — skip them (the lower-bound twin
        # of the causal upper bound), preserving packing's FLOP savings.
        kv_first = jnp.min(seg_start) // block_k

    def body(ki, carry):
        m, l, acc = carry
        k_blk = k_ref[pl.dslice(ki * block_k, block_k), :]
        v_blk = v_ref[pl.dslice(ki * block_k, block_k), :]
        # Native-dtype (bf16) operands feed the MXU directly; fp32
        # accumulation via preferred_element_type; scale after the dot.
        scores = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale   # [bq, bk]
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            # Large-negative (not -inf) keeps exp() finite with no NaN
            # guards on the hot path.
            scores = jnp.where(q_pos >= k_pos, scores, -1e30)
        if bias_ref is not None:
            scores = scores + bias_ref[0, pl.dslice(ki * block_k,
                                                    block_k)][None, :]
        if seg_start is not None:
            scores = _seg_mask(scores, seg_start, ki, block_k)
        new_m = jnp.maximum(m, jnp.max(scores, axis=1))
        alpha = jnp.exp(m - new_m)
        p = jnp.exp(scores - new_m[:, None])
        new_l = l * alpha + jnp.sum(p, axis=1)
        new_acc = acc * alpha[:, None] + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return new_m, new_l, new_acc

    m, l, acc = jax.lax.fori_loop(kv_first, n_kv_live, body, (m, l, acc))
    o_ref[:] = (acc / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)
    # log-sum-exp per row, consumed by the backward kernels.  lse_ref holds
    # the full row (TPU blocks must tile (8, 128)); write this q-block's
    # slice dynamically.
    lse_row = m + jnp.log(jnp.maximum(l, 1e-30))
    # lse lives as [8, S] per head (sublane-replicated) because TPU blocks
    # must tile (8, 128); row 0 is the value.
    lse_ref[:, pl.dslice(qi * block_q, block_q)] = jnp.broadcast_to(
        lse_row[None, :], (8, block_q))


def _bias_spec(bias, bh, s):
    """BlockSpec for the [B, 8, S] per-BATCH key bias: the grid runs over
    B*H, so the index map folds heads away instead of replicating the bias
    per head in HBM."""
    heads = bh // bias.shape[0]
    return pl.BlockSpec((None, 8, s), lambda b, i: (b // heads, 0, 0))


def _extras(bh, s, bias, seg):
    """(kwarg names, arrays, BlockSpecs) for the optional per-batch [B,8,S]
    sidebands — additive key bias and/or per-query segment starts."""
    names, arrays, specs = [], [], []
    if bias is not None:
        names.append("bias_ref")
        arrays.append(bias)
        specs.append(_bias_spec(bias, bh, s))
    if seg is not None:
        names.append("seg_ref")
        arrays.append(seg)
        specs.append(_bias_spec(seg, bh, s))
    return names, arrays, specs


def _with_extras(base_kernel, n_outs, names, **fixed):
    """Wrap a kernel so trailing sideband inputs arrive as keyword refs."""
    if not names:
        return functools.partial(base_kernel, **fixed)

    def kernel(*refs):
        # ref layout: positional inputs, sideband inputs, then outputs.
        n_extra = len(names)
        n_main = len(refs) - n_outs - n_extra
        main_in = refs[:n_main]
        extra = dict(zip(names, refs[n_main:n_main + n_extra]))
        outs = refs[n_main + n_extra:]
        base_kernel(*main_in, *outs, **fixed, **extra)

    return kernel


def _fwd(q, k, v, causal, sm_scale, bias=None, seg=None):
    # q, k, v: [BH, S, D]; bias/seg (optional): [B, 8, S] sidebands.
    bh, s, d = q.shape
    bq = _pick_block(s, BLOCK_Q)
    bk = _pick_block(s, BLOCK_K)
    grid = (bh, s // bq)
    names, arrays, bias_specs = _extras(bh, s, bias, seg)
    kernel = _with_extras(_fwd_kernel, 2, names, causal=causal,
                          sm_scale=sm_scale, block_k=bk)
    inputs = (q, k, v, *arrays)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, bq, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, s, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, s, d), lambda b, i: (b, 0, 0)),
        ] + bias_specs,
        out_specs=[
            pl.BlockSpec((None, bq, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, 8, s), lambda b, i: (b, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), q.dtype),
            jax.ShapeDtypeStruct((bh, 8, s), jnp.float32),
        ],
        interpret=_interpret(),
    )(*inputs)
    return out, lse


# ---------------------------------------------------------------------------
# Backward kernels
# ---------------------------------------------------------------------------

def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   *, causal, sm_scale, block_k, bias_ref=None,
                   seg_ref=None):
    qi = pl.program_id(1)
    block_q, d = q_ref.shape
    s = k_ref.shape[0]
    q = q_ref[:]
    do = do_ref[:]
    lse = lse_ref[0, pl.dslice(qi * block_q, block_q)]
    delta = delta_ref[0, pl.dslice(qi * block_q, block_q)]
    seg_start = None
    if seg_ref is not None:
        seg_start = seg_ref[0, pl.dslice(qi * block_q, block_q)]

    n_kv = s // block_k
    if causal:
        # ceil((qi+1)*bq / bk): every KV block touching or below the
        # diagonal, valid for ANY bq/bk ratio (bq < bk included).
        n_kv_live = jnp.minimum(
            ((qi + 1) * block_q + block_k - 1) // block_k, n_kv)
    else:
        n_kv_live = n_kv
    kv_first = 0
    if seg_start is not None:
        kv_first = jnp.min(seg_start) // block_k

    def body(ki, dq):
        k_blk = k_ref[pl.dslice(ki * block_k, block_k), :]
        v_blk = v_ref[pl.dslice(ki * block_k, block_k), :]
        scores = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            scores = jnp.where(q_pos >= k_pos, scores, -1e30)
        if bias_ref is not None:
            scores = scores + bias_ref[0, pl.dslice(ki * block_k,
                                                    block_k)][None, :]
        if seg_start is not None:
            scores = _seg_mask(scores, seg_start, ki, block_k)
        p = jnp.exp(scores - lse[:, None])
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = (p * (dp - delta[:, None]) * sm_scale).astype(k_blk.dtype)
        return dq + jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(kv_first, n_kv_live, body,
                           jnp.zeros((block_q, d), jnp.float32))
    dq_ref[:] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, *, causal, sm_scale, block_q,
                    bias_ref=None, seg_ref=None):
    ki = pl.program_id(1)
    block_k, d = k_ref.shape
    s = q_ref.shape[0]
    k_blk = k_ref[:]
    v_blk = v_ref[:]

    n_q = s // block_q
    if causal:
        # Query blocks strictly below the KV block's diagonal start.
        first_q = (ki * block_k) // block_q
    else:
        first_q = 0
    n_q_live = n_q
    if seg_ref is not None:
        # Packed rows: segment starts are NONDECREASING, so queries that
        # can see this KV block (seg_start <= kv block end) are a prefix
        # of rows — bound the loop instead of iterating fully-masked
        # blocks (the dkv twin of the fwd/dq kv_first skip).
        kv_end = (ki + 1) * block_k - 1
        valid_rows = jnp.sum(
            (seg_ref[0, :] <= kv_end).astype(jnp.int32))
        n_q_live = jnp.minimum(n_q, (valid_rows + block_q - 1) // block_q)

    def body(qi, carry):
        dk, dv = carry
        q_blk = q_ref[pl.dslice(qi * block_q, block_q), :]
        do_blk = do_ref[pl.dslice(qi * block_q, block_q), :]
        lse_blk = lse_ref[0, pl.dslice(qi * block_q, block_q)]
        delta_blk = delta_ref[0, pl.dslice(qi * block_q, block_q)]
        seg_blk = None
        if seg_ref is not None:
            seg_blk = seg_ref[0, pl.dslice(qi * block_q, block_q)]
        scores = jax.lax.dot_general(
            q_blk, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale   # [bq, bk]
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            scores = jnp.where(q_pos >= k_pos, scores, -1e30)
        if bias_ref is not None:
            # The KV grid owns a fixed key block: bias slice at this
            # kernel's own block index.
            scores = scores + bias_ref[0, pl.dslice(ki * block_k,
                                                    block_k)][None, :]
        if seg_blk is not None:
            scores = _seg_mask(scores, seg_blk, ki, block_k)
        p = jnp.exp(scores - lse_blk[:, None])
        pc = p.astype(do_blk.dtype)
        dv = dv + jax.lax.dot_general(
            pc, do_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do_blk, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = (p * (dp - delta_blk[:, None]) * sm_scale).astype(q_blk.dtype)
        dk = dk + jax.lax.dot_general(
            ds, q_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dk, dv

    dk, dv = jax.lax.fori_loop(
        first_q, n_q_live, body,
        (jnp.zeros((block_k, d), jnp.float32),
         jnp.zeros((block_k, d), jnp.float32)))
    dk_ref[:] = dk.astype(dk_ref.dtype)
    dv_ref[:] = dv.astype(dv_ref.dtype)


def _bwd_impl(causal, sm_scale, res, do, bias=None, seg=None, g_lse=None):
    q, k, v, out, lse = res
    bh, s, d = q.shape
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)  # [BH, S]
    if g_lse is not None:
        # lse cotangent folds into delta: dL/ds_ij = p_ij * (dp_ij -
        # delta_i + g_lse_i), so delta_eff = delta - g_lse feeds the
        # UNCHANGED backward kernels (dv = p^T do has no lse term).
        delta = delta - g_lse.astype(jnp.float32)
    # Same sublane-replicated [BH, 8, S] layout as lse (TPU block tiling).
    delta = jnp.broadcast_to(delta[:, None, :], delta.shape[:1] + (8,)
                             + delta.shape[1:])
    bq = _pick_block(s, BLOCK_Q)
    bk = _pick_block(s, BLOCK_K)
    names, bias_inputs, bias_specs = _extras(bh, s, bias, seg)

    dq_kernel = _with_extras(_bwd_dq_kernel, 1, names, causal=causal,
                             sm_scale=sm_scale, block_k=bk)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(bh, s // bq),
        in_specs=[
            pl.BlockSpec((None, bq, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, s, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, s, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, bq, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, 8, s), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, 8, s), lambda b, i: (b, 0, 0)),
        ] + bias_specs,
        out_specs=pl.BlockSpec((None, bq, d), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, s, d), q.dtype),
        interpret=_interpret(),
    )(q, k, v, do, lse, delta, *bias_inputs)

    dkv_kernel = _with_extras(_bwd_dkv_kernel, 2, names, causal=causal,
                              sm_scale=sm_scale, block_q=bq)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(bh, s // bk),
        in_specs=[
            pl.BlockSpec((None, s, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, bk, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, bk, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, s, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, 8, s), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, 8, s), lambda b, i: (b, 0, 0)),
        ] + bias_specs,
        out_specs=[
            pl.BlockSpec((None, bk, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, bk, d), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), k.dtype),
            jax.ShapeDtypeStruct((bh, s, d), v.dtype),
        ],
        interpret=_interpret(),
    )(q, k, v, do, lse, delta, *bias_inputs)
    return dq, dk, dv


def _bwd(causal, sm_scale, res, do):
    return _bwd_impl(causal, sm_scale, res, do)


# ---------------------------------------------------------------------------
# custom_vjp plumbing + public API
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash(q, k, v, causal, sm_scale):
    out, _ = _fwd(q, k, v, causal, sm_scale)
    return out


def _flash_fwd(q, k, v, causal, sm_scale):
    out, lse = _fwd(q, k, v, causal, sm_scale)
    return out, (q, k, v, out, lse)


_flash.defvjp(_flash_fwd, _bwd)


def _flat_layout(q, k, v):
    """[B, S, H, D] -> the kernels' flat [B*H, S, D] operands, GQA KV
    heads repeated to Hq (shared by both public entry points)."""
    B, S, Hq, D = q.shape
    Hkv = k.shape[2]
    if Hkv != Hq:
        k = jnp.repeat(k, Hq // Hkv, axis=2)
        v = jnp.repeat(v, Hq // Hkv, axis=2)

    def t(x):
        return x.transpose(0, 2, 1, 3).reshape(B * Hq, S, D)

    return t(q), t(k), t(v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash_lse(q, k, v, causal, sm_scale):
    """Like ``_flash`` but ALSO returns the per-row log-sum-exp [BH, S]
    as a differentiable output — the merge statistic blockwise consumers
    (ring attention) need to combine partial attentions."""
    out, lse = _fwd(q, k, v, causal, sm_scale)
    return out, lse[:, 0, :]


def _flash_lse_fwd(q, k, v, causal, sm_scale):
    out, lse = _fwd(q, k, v, causal, sm_scale)
    return (out, lse[:, 0, :]), (q, k, v, out, lse)


def _flash_lse_bwd(causal, sm_scale, res, cts):
    do, g_lse = cts
    return _bwd_impl(causal, sm_scale, res, do, g_lse=g_lse)


_flash_lse.defvjp(_flash_lse_fwd, _flash_lse_bwd)


def _pad_head_dim(q, k, v):
    """Zero-pad D to the next MXU tile (64).  Zero dims contribute
    nothing to any q·k score, so the padded kernel computes identical
    attention PROVIDED the caller threads the TRUE head dim's softmax
    scale through as the kernel's fp32 ``sm_scale`` (a nondiff Python
    float).  It must NOT be folded into q: pre-multiplying by a
    ``q.dtype``-rounded ``sqrt(Dpad)/sqrt(D)`` constant perturbs every
    score's softmax temperature in bf16 (~0.4% max), smearing padded vs
    dense parity.  Autodiff slices the grads back through the pad
    (grad-of-pad = slice).  Returns padded (q, k, v)."""
    d = q.shape[-1]
    dp = -(-d // 64) * 64
    pad = ((0, 0), (0, 0), (0, 0), (0, dp - d))
    return jnp.pad(q, pad), jnp.pad(k, pad), jnp.pad(v, pad)


def flash_attention_lse(q, k, v, *, causal: bool = True,
                        _sm_scale: Optional[float] = None):
    """Flash attention returning ``(out [B,S,H,D], lse [B,H,S] fp32)``.

    The lse output makes partial attentions COMPOSABLE: blockwise
    consumers (ring attention over a sequence-sharded mesh) merge per-
    block results as ``out = sum_t exp(lse_t - logsumexp_t(lse)) out_t``
    and AD flows through both outputs (the lse cotangent folds into the
    backward kernels' delta sideband — see ``_bwd_impl``).

    Kernel-only surface: requires S % 128 == 0 (no dense fallback, no
    sequence padding — a blockwise caller owns the sequence layout, so
    callers check ``flash_lse_supported`` and keep their own fallback;
    a silent dense path would defeat the memory contract the caller is
    composing for).  Off-tile head dims ARE handled: D % 64 != 0 is
    zero-padded to the next MXU tile and sliced back (zero dims change
    neither the scores nor the lse; the TRUE head dim's 1/sqrt(D) rides
    through as the kernel's fp32 sm_scale rather than a q.dtype-rounded
    multiplier on q — see ``_pad_head_dim``), so ring attention keeps
    its per-hop kernel for small-head models.
    """
    B, S, Hq, D = q.shape
    if not flash_lse_supported(S, D):
        raise ValueError(
            f"flash_attention_lse requires S % 128 == 0, "
            f"got S={S}, D={D}; gate on flash_lse_supported()")
    if D % 64 != 0:
        qp, kp, vp = _pad_head_dim(q, k, v)
        out, lse = flash_attention_lse(
            qp, kp, vp, causal=causal,
            _sm_scale=_sm_scale if _sm_scale is not None
            else 1.0 / math.sqrt(D))
        return out[..., :D], lse
    sm_scale = _sm_scale if _sm_scale is not None else 1.0 / math.sqrt(D)
    qt, kt, vt = _flat_layout(q, k, v)
    out, lse = _flash_lse(qt, kt, vt, causal, sm_scale)
    return (out.reshape(B, Hq, S, D).transpose(0, 2, 1, 3),
            lse.reshape(B, Hq, S))


def flash_lse_supported(S: int, D: int) -> bool:
    """Shapes the lse-returning kernel path accepts (off-tile D is
    padded internally; S stays strict — the blockwise caller owns the
    sequence layout)."""
    return S % 128 == 0 and _pick_block(S, BLOCK_Q) > 0


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _flash_biased(q, k, v, bias, causal, sm_scale):
    out, _ = _fwd(q, k, v, causal, sm_scale, bias)
    return out


def _flash_biased_fwd(q, k, v, bias, causal, sm_scale):
    out, lse = _fwd(q, k, v, causal, sm_scale, bias)
    return out, (q, k, v, bias, out, lse)


def _flash_biased_bwd(causal, sm_scale, res, do):
    q, k, v, bias, out, lse = res
    dq, dk, dv = _bwd_impl(causal, sm_scale, (q, k, v, out, lse), do,
                           bias=bias)
    # The bias is a constant mask encoding (0 / -1e30); no useful gradient.
    return dq, dk, dv, jnp.zeros_like(bias)


_flash_biased.defvjp(_flash_biased_fwd, _flash_biased_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _flash_seg(q, k, v, seg, causal, sm_scale):
    out, _ = _fwd(q, k, v, causal, sm_scale, seg=seg)
    return out


def _flash_seg_fwd(q, k, v, seg, causal, sm_scale):
    out, lse = _fwd(q, k, v, causal, sm_scale, seg=seg)
    return out, (q, k, v, seg, out, lse)


def _flash_seg_bwd(causal, sm_scale, res, do):
    import numpy as np

    q, k, v, seg, out, lse = res
    dq, dk, dv = _bwd_impl(causal, sm_scale, (q, k, v, out, lse), do,
                           seg=seg)
    # Integer input: JAX requires a float0 cotangent.
    return dq, dk, dv, np.zeros(seg.shape, dtype=jax.dtypes.float0)


_flash_seg.defvjp(_flash_seg_fwd, _flash_seg_bwd)


def _segment_starts(segment_ids):
    """[B, S] segment ids (contiguous runs) -> [B, S] int32 index of each
    position's segment start, via a cummax over run boundaries."""
    B, S = segment_ids.shape
    pos = jnp.arange(S, dtype=jnp.int32)
    change = jnp.concatenate(
        [jnp.ones((B, 1), bool),
         segment_ids[:, 1:] != segment_ids[:, :-1]], axis=1)
    return jax.lax.cummax(
        jnp.where(change, pos[None, :], 0).astype(jnp.int32), axis=1)


def _supported(S: int, D: int) -> bool:
    # D=64 (BERT-family head dim) runs at reduced lane utilization (Mosaic
    # pads the minor dim) but still beats XLA's dense attention on-chip:
    # measured 1.25x at S=2048 and 1.6x at S=4096 (bf16, masked).
    # S is NOT constrained here: off-tile sequence lengths are padded to
    # the next multiple of 128 in flash_attention (see _pad_to_tile).
    return D % 64 == 0


def _pad_to_tile(q, k, v, causal, key_padding_mask, segment_ids):
    """Zero-pad the sequence dim to the next multiple of 128 and arrange
    masking so padded KEYS are never attended: pure-causal configs exclude
    trailing positions via the causal triangle already; masked configs get
    the pad marked invalid; bare bidirectional configs gain a key-padding
    mask; packed configs put the pad in a fresh trailing segment.  Padded
    QUERY rows produce garbage that the caller slices off, and their
    upstream cotangents are exactly zero (the slice's transpose), so they
    contribute nothing to dQ/dK/dV."""
    B, S = q.shape[:2]
    pad = -S % 128
    zpad = lambda t: jnp.pad(t, ((0, 0), (0, pad)) + ((0, 0),) * (t.ndim - 2))
    q, k, v = zpad(q), zpad(k), zpad(v)
    if segment_ids is not None:
        segment_ids = jnp.concatenate(
            [segment_ids,
             jnp.broadcast_to(segment_ids[:, -1:] + 1, (B, pad))], axis=1)
    elif key_padding_mask is not None:
        key_padding_mask = zpad(key_padding_mask)  # zero-pad == False
    elif not causal:
        key_padding_mask = jnp.concatenate(
            [jnp.ones((B, S), bool), jnp.zeros((B, pad), bool)], axis=1)
    return q, k, v, key_padding_mask, segment_ids


def flash_attention(q, k, v, *, causal: bool = True,
                    key_padding_mask=None, segment_ids=None,
                    _sm_scale: Optional[float] = None):
    """Flash attention on [B, S, H, D] tensors (the model zoo seam).

    ``key_padding_mask``: optional [B, S] boolean (True = attend to that
    key) — BERT-style padding masks; carried through the kernel as an
    additive key bias in the same sublane-replicated layout as the LSE.
    ``segment_ids``: optional [B, S] integer ids of contiguous packed
    sequences (causal only, exclusive with the padding mask): each query
    attends only within its own segment — block-diagonal causal attention
    for packed pretraining, at O(S) sideband cost instead of an [S, S]
    mask.  GQA (fewer KV heads) is handled by repeating KV heads.

    Off-tile sequence lengths (S not a multiple of 128) are zero-padded to
    the next tile and sliced back, so BERT/packed configs one token off
    the block size keep the kernel.  Head dims off the MXU tiling (D not
    a multiple of 64) are likewise zero-padded to the next multiple of 64
    and sliced back — zero dims contribute nothing to the scores, and the
    TRUE head dim's 1/sqrt(D) is threaded through as the kernel's fp32
    sm_scale (never a q.dtype-rounded multiplier on q, which would shift
    every score's softmax temperature in bf16; see ``_pad_head_dim``) —
    so small-head models keep
    the kernel and its O(S) memory contract instead of materializing the
    [B, H, S, S] dense scores (measured 1.2x faster than the dense path
    at D=32, S=4096 fwd+bwd on v5e, and the only option that does not
    OOM at long S).  ``fallback_count`` still tracks the composing
    callers' own fallbacks (:func:`flash_attention_lse` keeps its strict
    no-shim contract).

    Fully-masked query rows (every key excluded by ``key_padding_mask``)
    produce UNDEFINED outputs — the -1e30 mask bias and the -1e30 running
    max cancel, yielding uniform attention over the masked keys — and, if
    given nonzero upstream cotangents, contribute garbage to dK/dV.  This
    matches the dense fallback's behavior; callers must not consume such
    rows (standard BERT practice masks them out of the loss).
    """
    B, S, Hq, D = q.shape
    if segment_ids is not None:
        if not causal:
            raise NotImplementedError(
                "segment_ids implies packed causal attention; bidirectional"
                " segment masking is not supported")
        if key_padding_mask is not None:
            raise NotImplementedError(
                "segment_ids and key_padding_mask are mutually exclusive "
                "(mark padding as its own trailing segment instead)")
    if not _supported(S, D):
        qp, kp, vp = _pad_head_dim(q, k, v)  # see _pad_head_dim
        out = flash_attention(
            qp, kp, vp, causal=causal,
            key_padding_mask=key_padding_mask, segment_ids=segment_ids,
            _sm_scale=_sm_scale if _sm_scale is not None
            else 1.0 / math.sqrt(D))
        return out[..., :D]
    if S % 128 != 0:
        q, k, v, key_padding_mask, segment_ids = _pad_to_tile(
            q, k, v, causal, key_padding_mask, segment_ids)
        return flash_attention(
            q, k, v, causal=causal, key_padding_mask=key_padding_mask,
            segment_ids=segment_ids, _sm_scale=_sm_scale)[:, :S]
    sm_scale = _sm_scale if _sm_scale is not None else 1.0 / math.sqrt(D)
    qt, kt, vt = _flat_layout(q, k, v)
    if segment_ids is not None:
        starts = _segment_starts(jnp.asarray(segment_ids))
        # [B, S] -> [B, 8, S]: sublane-replicated (TPU tiling); heads are
        # folded away in the kernels' sideband BlockSpec.
        seg = jnp.broadcast_to(starts[:, None, :], (B, 8, S))
        out = _flash_seg(qt, kt, vt, seg, causal, sm_scale)
    elif key_padding_mask is None:
        out = _flash(qt, kt, vt, causal, sm_scale)
    else:
        bias = jnp.where(key_padding_mask, 0.0, -1e30).astype(jnp.float32)
        bias = jnp.broadcast_to(bias[:, None, :], (B, 8, S))
        out = _flash_biased(qt, kt, vt, bias, causal, sm_scale)
    return out.reshape(B, Hq, S, D).transpose(0, 2, 1, 3)


def flash_attention_fn(q, k, v, mask=None, **kwargs):
    """Adapter matching the model zoo's pluggable ``attention_fn``.

    ``mask`` follows the zoo's convention (broadcastable [B, 1, 1, S]
    key-padding mask, True = attend; what BertEncoder passes).  With a
    mask the attention is bidirectional-masked (BERT semantics); without
    one it is causal (decoder semantics).  Richer mask structures
    (arbitrary [B, H, S, S]) are not supported by the kernel — use the
    dense path for those."""
    if mask is None:
        return flash_attention(q, k, v, causal=True)
    mask = jnp.asarray(mask)
    if mask.ndim == 4 and mask.shape[1] == 1 and mask.shape[2] == 1:
        key_mask = mask[:, 0, 0, :]
    elif mask.ndim == 2:
        key_mask = mask
    else:
        raise NotImplementedError(
            "flash_attention_fn supports key-padding masks ([B, S] or "
            "[B, 1, 1, S]); got shape " + str(mask.shape) + " — use the "
            "dense attention path for richer mask structures"
        )
    return flash_attention(q, k, v, causal=False,
                           key_padding_mask=key_mask.astype(bool))
