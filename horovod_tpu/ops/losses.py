"""Loss ops tuned for TPU memory traffic.

No reference equivalent (the reference has no loss library); this exists
because the naive causal-LM loss — ``log_softmax`` then gather —
materializes a full fp32 log-probability tensor the size of the logits
([B, S, V]; 2 GB at B=8, S=2048, V=32k) and then re-reads it, making the
loss a multi-gigabyte HBM round trip.  ``softmax_cross_entropy`` computes
``logsumexp(logits) - logits[target]`` instead: XLA fuses the fp32
convert into the reduction passes over the (bf16) logits and no
logits-sized fp32 tensor is ever written.  Same math, same gradients
(d/dlogits = softmax - onehot via autodiff of the lse), measured ~4%
step-time win on the 400M-param Llama bench config on one v5e.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["softmax_cross_entropy"]


def softmax_cross_entropy(logits, targets, *, where=None,
                          reduction: str = "mean"):
    """Token cross-entropy from (possibly bf16) logits.

    ``logits``: [..., V]; ``targets``: integer [...]; ``where``: optional
    boolean [...] mask of tokens to include (packing/padding).  Returns a
    scalar fp32 ``reduction`` ("mean" over selected tokens, or "sum" —
    the form sharded losses need when the mean denominator is the GLOBAL
    token count psummed outside).
    """
    if reduction not in ("mean", "sum"):
        raise ValueError(f"unknown reduction {reduction!r}")
    logits32 = logits.astype(jnp.float32)
    lse = jax.nn.logsumexp(logits32, axis=-1)
    tgt = jnp.take_along_axis(
        logits32, targets[..., None].astype(jnp.int32), axis=-1)[..., 0]
    nll = lse - tgt
    if where is not None:
        nll = jnp.where(where, nll, 0.0)
    if reduction == "sum":
        return jnp.sum(nll)
    if where is not None:
        return jnp.sum(nll) / jnp.maximum(jnp.sum(where), 1)
    return jnp.mean(nll)
