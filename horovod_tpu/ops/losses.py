"""Loss ops tuned for TPU memory traffic.

No reference equivalent (the reference has no loss library); this exists
because the naive causal-LM loss — ``log_softmax`` then gather —
materializes a full fp32 log-probability tensor the size of the logits
([B, S, V]; 2 GB at B=8, S=2048, V=32k) and then re-reads it, making the
loss a multi-gigabyte HBM round trip.

``softmax_cross_entropy`` computes ``logsumexp(logits) - logits[target]``
with a custom VJP whose residuals are the logits AS GIVEN (bf16 when the
model's head emits bf16 — ``LlamaConfig.logits_dtype``) plus the tiny
fp32 lse ``[B, S]``:

* forward: the fp32 upcast fuses into the reduction passes over the
  logits, so the only logits-sized tensor in memory is the model's own
  output;
* backward: ``softmax - onehot`` is recomputed from those residuals and
  the cotangent is emitted in the logits dtype, so the grad matmuls
  (dW, dX) read half-width operands.

Versus plain autodiff of the lse form (which stores an f32 copy of the
logits and emits an f32 cotangent), this halves every logits-sized
tensor's bytes when the head computes in bf16.  Same math; gradients
match autodiff to bf16 rounding (tests/test_losses.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["softmax_cross_entropy"]


def _nll_impl(logits, targets):
    # Hand-rolled logsumexp: max and gather read the logits dtype
    # directly, and the f32 upcast has exactly ONE consumer (the exp-sum
    # reduce), so XLA fuses the convert into the reduction pass instead
    # of materializing an f32 copy of the logits for multiple readers
    # (profiled: jax.nn.logsumexp over the upcast wrote an f32 [B,S,V]).
    m = jax.lax.stop_gradient(jnp.max(logits, axis=-1)).astype(jnp.float32)
    s = jnp.sum(jnp.exp(logits.astype(jnp.float32) - m[..., None]), axis=-1)
    lse = m + jnp.log(s)
    tgt = jnp.take_along_axis(
        logits, targets[..., None].astype(jnp.int32),
        axis=-1)[..., 0].astype(jnp.float32)
    return lse - tgt, lse


@jax.custom_vjp
def _nll(logits, targets):
    """Per-token negative log-likelihood [...], from logits [..., V]."""
    return _nll_impl(logits, targets)[0]


def _nll_fwd(logits, targets):
    nll, lse = _nll_impl(logits, targets)
    return nll, (logits, targets, lse)


def _nll_bwd(res, g):
    logits, targets, lse = res
    # softmax recomputed from the saved (possibly bf16) logits + f32 lse;
    # the onehot subtraction fuses as iota==target, so nothing V-sized
    # materializes beyond the returned cotangent — which is emitted in
    # the logits dtype so the downstream dW/dX matmuls read half-width.
    p = jnp.exp(logits.astype(jnp.float32) - lse[..., None])
    onehot = (jax.lax.broadcasted_iota(jnp.int32, logits.shape,
                                       logits.ndim - 1)
              == targets[..., None].astype(jnp.int32))
    d = (p - onehot.astype(jnp.float32)) * g[..., None].astype(jnp.float32)
    return d.astype(logits.dtype), None


_nll.defvjp(_nll_fwd, _nll_bwd)


def softmax_cross_entropy(logits, targets, *, where=None,
                          reduction: str = "mean"):
    """Token cross-entropy from (possibly bf16) logits.

    ``logits``: [..., V]; ``targets``: integer [...]; ``where``: optional
    boolean [...] mask of tokens to include (packing/padding).  Returns a
    scalar fp32 ``reduction`` ("mean" over selected tokens, or "sum" —
    the form sharded losses need when the mean denominator is the GLOBAL
    token count psummed outside).
    """
    if reduction not in ("mean", "sum"):
        raise ValueError(f"unknown reduction {reduction!r}")
    # Reverse-mode only: the custom_vjp that keeps the residuals bf16
    # forfeits forward-mode AD (jax.jvp/jax.hessian over this op raise).
    nll = _nll(logits, targets)
    if where is not None:
        nll = jnp.where(where, nll, 0.0)
    if reduction == "sum":
        return jnp.sum(nll)
    if where is not None:
        return jnp.sum(nll) / jnp.maximum(jnp.sum(where), 1)
    return jnp.mean(nll)
