"""Ragged (per-device variable dim-0) allgather for the jit path.

SURVEY.md §3.5 names the design constraint: the reference's allgather
negotiates per-rank dim-0 sizes at runtime (reference
``operations.cc:796-856``), but XLA programs are compiled with static
shapes — under SPMD every device runs the SAME program, so a traced
collective cannot have per-device shapes at all.

The TPU-native answer (the "pad-to-max + size sideband, with
recompilation bucketing" recipe):

* every device carries a buffer padded to a STATIC row capacity plus a
  scalar count of valid rows;
* :func:`ragged_allgather` gathers both (one ``all_gather`` each) and
  masks invalid rows, returning ``(gathered [N, cap, ...], sizes [N])``;
* :func:`bucket_rows` rounds a row count up to a power-of-two bucket so
  varying raggedness hits a handful of compiled programs instead of one
  per distinct size;
* :func:`compact` (host-side) drops the padding using the gathered sizes.

The eager path needs none of this — the engine negotiates true sizes at
runtime (``horovod_tpu/cpp/engine.cc`` ExecAllgather).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["bucket_rows", "pad_rows", "ragged_allgather", "compact"]


def bucket_rows(n: int, *, min_bucket: int = 8) -> int:
    """Smallest power-of-two >= n (and >= min_bucket): the static row
    capacity to pad to.  Bounded recompilation: k distinct bucket sizes
    cover any raggedness with at most k compiled programs."""
    if n <= min_bucket:
        return min_bucket
    return 1 << (int(n) - 1).bit_length()


def pad_rows(x, capacity: int):
    """Zero-pad dim 0 of host array ``x`` to ``capacity`` rows; returns
    ``(padded, n_valid)``.  Call before device_put / shard_map."""
    x = np.asarray(x)
    n = x.shape[0]
    if n > capacity:
        raise ValueError(f"{n} rows exceed the bucket capacity {capacity}")
    pad = np.zeros((capacity - n,) + x.shape[1:], dtype=x.dtype)
    return np.concatenate([x, pad], axis=0), n


def ragged_allgather(x_padded, n_valid, *, axis_name="data"):
    """Inside shard_map: gather per-device padded buffers AND their valid
    row counts.

    ``x_padded``: [cap, ...] — this device's rows, zero-padded to the
    static capacity.  ``n_valid``: scalar int32 of real rows.  Returns
    ``(gathered [N, cap, ...], sizes [N])`` with invalid rows zeroed, so
    SUMS over the gathered buffer are already correct (for means divide by
    ``sizes.sum()``, not the padded element count) and :func:`compact` can
    drop padding on the host.
    """
    cap = x_padded.shape[0]
    mask = (jnp.arange(cap) < n_valid).astype(x_padded.dtype)
    mask = mask.reshape((cap,) + (1,) * (x_padded.ndim - 1))
    gathered = jax.lax.all_gather(x_padded * mask, axis_name)
    sizes = jax.lax.all_gather(jnp.asarray(n_valid, jnp.int32), axis_name)
    return gathered, sizes


def compact(gathered, sizes):
    """Host-side: concatenate only the valid rows of each device's block
    (the shape-dynamic step XLA cannot express)."""
    gathered = np.asarray(gathered)
    sizes = np.asarray(sizes)
    return np.concatenate(
        [gathered[i, : sizes[i]] for i in range(gathered.shape[0])], axis=0)
