"""JAX frontend: the flagship user API of the TPU-native Horovod rebuild.

Reference parity: ``horovod/tensorflow/__init__.py`` (225 LoC) — ``init``,
rank queries, ``allreduce``, ``broadcast_global_variables``,
``DistributedOptimizer`` — re-thought for JAX's functional model:

* ``DistributedOptimizer`` wraps an *optax* ``GradientTransformation``; the
  wrapped ``update`` fuses and psums gradients over the mesh's data axes
  before the inner optimizer sees them.  This is the exact analogue of the
  reference overriding ``compute_gradients`` to allreduce each grad
  (tensorflow/__init__.py:183-209), but it happens inside ``jit`` where XLA
  overlaps the ICI collectives with remaining backward compute — the same
  overlap the reference engineered by hand with its background thread.
* ``broadcast_parameters`` replaces ``BroadcastGlobalVariablesHook``:
  functional in, functional out (no sessions, no variable mutation).
* Collectives dispatch on context: on tracers (inside jit/shard_map) they are
  single XLA ops over a named axis; on concrete arrays they go through the
  eager runtime engine (negotiation across processes), matching the
  reference's eager TF path.

Typical use::

    import horovod_tpu.jax as hvd
    hvd.init()
    mesh = hvd.data_parallel_mesh()
    opt = hvd.DistributedOptimizer(optax.sgd(0.01 * hvd.num_chips()))
    step = hvd.make_train_step(loss_fn, opt, mesh)
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
from jax.sharding import Mesh, PartitionSpec

from horovod_tpu.common.jax_compat import shard_map

from horovod_tpu.common import (
    epoch,
    init,
    is_initialized,
    local_rank,
    local_size,
    mpi_threads_supported,
    rank,
    shutdown,
    size,
)
from horovod_tpu.ops import collective_ops as _cops
from horovod_tpu.ops.collective_ops import (
    Average,
    Max,
    Min,
    Product,
    ReduceOp,
    Sum,
)
from horovod_tpu.ops.compression import Compression
from horovod_tpu.ops.fusion import fuse_apply
from horovod_tpu.parallel import mesh as _mesh
from horovod_tpu.parallel.mesh import (
    build_mesh,
    data_parallel_mesh,
    default_mesh,
    use_mesh,
)

__all__ = [
    "init", "shutdown", "is_initialized", "rank", "size", "local_rank",
    "local_size", "epoch", "mpi_threads_supported",
    "num_chips", "local_devices",
    "allreduce", "grouped_allreduce", "allgather", "broadcast",
    "reducescatter", "alltoall",
    "Average", "Sum", "Min", "Max", "Product", "ReduceOp", "Compression",
    "DistributedOptimizer", "allreduce_gradients",
    "broadcast_parameters", "broadcast_optimizer_state",
    "build_mesh", "data_parallel_mesh", "default_mesh", "use_mesh",
    "make_train_step",
]


def num_chips() -> int:
    """Total number of TPU chips across all processes (the unit the
    reference calls ``size`` when run one-process-per-GPU)."""
    return jax.device_count()


def local_devices():
    return jax.local_devices()


def _is_traced(x) -> bool:
    return isinstance(x, jax.core.Tracer)


# ---------------------------------------------------------------------------
# Collectives (context-dispatching wrappers)
# ---------------------------------------------------------------------------

def allreduce(tensor, *, axis_name="data", op=Average, average=None,
              compression=Compression.none, name=None, priority=None):
    """Allreduce. Inside jit/shard_map: one XLA collective over ``axis_name``.

    On concrete values: process-level eager allreduce through the runtime
    engine (identity at size()==1, like the reference under ``-np 1``).
    ``priority`` (host path only; 0 = most urgent) overrides the
    scheduling priority the priority-banded coordinator
    (HOROVOD_PRIORITY_BANDS) orders responses by — every rank must pass
    the same value for a given name.
    """
    if _is_traced(tensor):
        return _cops.allreduce(
            tensor, axis_name=axis_name, op=op, average=average,
            compression=compression,
        )
    from horovod_tpu.runtime import eager

    return eager.allreduce(tensor, op=op, average=average,
                           compression=compression, name=name,
                           priority=priority)


def grouped_allreduce(tensors, *, axis_name="data", op=Average,
                      compression=Compression.none, name=None):
    if tensors and _is_traced(tensors[0]):
        return _cops.grouped_allreduce(
            tensors, axis_name=axis_name, op=op, compression=compression
        )
    from horovod_tpu.runtime import eager

    return eager.grouped_allreduce(tensors, op=op, compression=compression,
                                   name=name)


def allgather(tensor, *, axis_name="data", axis=0, name=None):
    if _is_traced(tensor):
        return _cops.allgather(tensor, axis_name=axis_name, axis=axis)
    from horovod_tpu.runtime import eager

    return eager.allgather(tensor, name=name)


def broadcast(tensor, root_rank=0, *, axis_name="data", name=None):
    if _is_traced(tensor):
        return _cops.broadcast(tensor, root_rank, axis_name=axis_name)
    from horovod_tpu.runtime import eager

    return eager.broadcast(tensor, root_rank=root_rank, name=name)


def reducescatter(tensor, *, axis_name="data", op=Sum, scatter_axis=0,
                  tiled=True, name=None):
    """Reduce-scatter.  Traced: one XLA psum_scatter over ``axis_name``.
    Eager: cross-process ring reduce-scatter through the runtime engine.

    Full axis generality on BOTH paths (``scatter_axis``/``tiled`` match
    ``lax.psum_scatter``): the eager engine scatters dim-0 rows, so other
    axes ride a moveaxis shim around the wire op; ``tiled=False`` removes
    the scattered axis (its length must equal ``size()``)."""
    if _is_traced(tensor):
        return _cops.reducescatter(tensor, axis_name=axis_name, op=op,
                                   scatter_axis=scatter_axis, tiled=tiled)
    import jax.numpy as jnp

    x = jnp.asarray(tensor)
    if not tiled and x.shape[scatter_axis] != size():
        raise ValueError(
            f"tiled=False requires dim {scatter_axis} (length "
            f"{x.shape[scatter_axis]}) to equal size() ({size()}), like "
            "lax.psum_scatter")
    if size() == 1:
        # World of one: reduce is identity, the scatter keeps the full
        # shard — for any op/axis (matches the reference under -np 1).
        return jnp.squeeze(x, scatter_axis) if not tiled else x
    from horovod_tpu.runtime import eager

    moved = jnp.moveaxis(x, scatter_axis, 0)
    out = eager.reducescatter(moved, op=op, name=name)
    out = jnp.moveaxis(out, 0, scatter_axis)
    return jnp.squeeze(out, scatter_axis) if not tiled else out


def alltoall(tensor, *, axis_name="seq", split_axis=0, concat_axis=0,
             name=None, splits=None, wire_dtype=None, priority=None):
    """All-to-all.  Traced: one XLA all_to_all over ``axis_name``.  Eager:
    cross-process ring exchange of equal blocks, axis-general via a
    moveaxis shim (the wire op exchanges dim-0 blocks): split ``tensor``
    into ``size()`` blocks along ``split_axis``; block i goes to rank i;
    the received blocks concatenate along ``concat_axis`` — same
    semantics as ``lax.all_to_all`` on the traced path.

    ``splits`` (eager, dim 0 only) sends VARIABLE per-rank row counts —
    the MoE dispatch/combine primitive; the output's dim 0 is this
    rank's column of the negotiated size matrix, so it is data-dependent
    and only available eagerly."""
    if _is_traced(tensor):
        if splits is not None:
            raise NotImplementedError(
                "variable splits are eager-only (the output shape is "
                "data-dependent; XLA all_to_all exchanges equal blocks)")
        return _cops.alltoall(tensor, axis_name=axis_name,
                              split_axis=split_axis, concat_axis=concat_axis)
    import jax.numpy as jnp

    x = jnp.asarray(tensor)
    if size() == 1:
        return x
    from horovod_tpu.runtime import eager

    if splits is not None and (split_axis != 0 or concat_axis != 0):
        raise NotImplementedError(
            "variable splits address dim-0 rows; use "
            "split_axis=0, concat_axis=0")
    if split_axis == 0 and concat_axis == 0:
        return eager.alltoall(x, name=name, splits=splits,
                              wire_dtype=wire_dtype,
                              priority=priority)  # wire semantics, copy-free
    moved = jnp.moveaxis(x, split_axis, 0)
    z = eager.alltoall(moved, name=name)
    # z: size() received blocks stacked along dim 0, each the moved shape
    # with dim 0 shrunk by size().  Restore each block's axis order, then
    # concatenate where the caller asked.
    blocks = jnp.split(z, size(), axis=0)
    blocks = [jnp.moveaxis(b, 0, split_axis) for b in blocks]
    return jnp.concatenate(blocks, axis=concat_axis)


# ---------------------------------------------------------------------------
# Gradient reduction + DistributedOptimizer
# ---------------------------------------------------------------------------

def allreduce_gradients(grads, *, axis_name=None, op=Average,
                        compression=Compression.none,
                        fusion_threshold_bytes=None, wire_policy=None):
    """Fused allreduce of a gradient pytree over the data axes.

    ``axis_name`` may be a name, tuple of names, or None (= every data-like
    axis of the default mesh: ``data`` and ``fsdp``).

    Traced gradients (inside jit/shard_map) reduce as fused XLA
    collectives.  CONCRETE gradients — the host-driven DCN path — go
    through the eager engine per leaf, with stable tree-path names: that
    is what lets ``compression=Compression.topk(...)`` keep one
    error-feedback residual per gradient leaf, and the wire-level
    compressors (``Compression.wire_int8`` etc.) negotiate their wire
    dtype per tensor.

    On the host path every leaf is additionally stamped with a
    scheduling PRIORITY equal to its registration (tree-flatten) order —
    first-registered ≈ front layer ≈ needed first by the NEXT step's
    forward — which the priority-banded coordinator
    (HOROVOD_PRIORITY_BANDS) uses to dispatch urgent gradients first.

    ``wire_policy`` (a :class:`horovod_tpu.runtime.wire_policy.WirePolicy`;
    default: the env-configured policy when HOROVOD_WIRE_POLICY=1, else
    off) chooses a per-leaf wire dtype from rolling gradient statistics
    (int8 for large embedding-shaped grads, fp32 for norm/bias leaves),
    stamped as ADVISORY per-tensor overrides so per-rank statistics can
    never split negotiation.
    """
    leaves = jax.tree.leaves(grads)
    if leaves and not _is_traced(leaves[0]):
        from horovod_tpu.ops.compression import TopKCompressor
        from horovod_tpu.runtime import eager
        from horovod_tpu.runtime import wire_policy as _wp

        if wire_policy is None and _wp.policy_enabled():
            wire_policy = _wp.default_policy()
        flat, treedef = jax.tree_util.tree_flatten_with_path(grads)
        if isinstance(compression, TopKCompressor):
            # Sparse path: per-leaf residuals keyed by stable tree-path
            # names.  Sequential by nature (each leaf is two allgathers
            # plus a host scatter-add) — top-k is the opt-in
            # bandwidth-starved regime where that trade is the point.
            out = [
                eager.allreduce(
                    leaf, op=op, compression=compression,
                    name="grad" + (jax.tree_util.keystr(path) or f".{i}"))
                for i, (path, leaf) in enumerate(flat)
            ]
        else:
            # Dense/wire path: enqueue every leaf before draining any —
            # one negotiation cycle covers the burst and the engine's
            # response fusion batches same-dtype/same-wire leaves into
            # few ring collectives (a per-leaf synchronous loop would
            # serialize N round trips and defeat fusion entirely).
            # Priorities = registration order; the wire policy (when on)
            # stamps advisory per-leaf formats keyed by the same stable
            # tree-path names the top-k residuals use.  The original
            # leaves go to grouped_allreduce unchanged, so the default
            # (policy-off) path is exactly the pre-policy one; with the
            # policy ON, the statistics cost one extra host fetch per
            # leaf — the bounded, opt-in price of observing gradients.
            wire_dtypes = None
            if wire_policy is not None:
                import numpy as _np

                wire_dtypes = [
                    wire_policy.observe_and_choose(
                        "grad" + (jax.tree_util.keystr(path) or f".{i}"),
                        _np.asarray(leaf))
                    for i, (path, leaf) in enumerate(flat)
                ]
            out = eager.grouped_allreduce(
                [leaf for _, leaf in flat], op=op,
                compression=compression, name="grad",
                priorities=list(range(len(flat))),
                wire_dtypes=wire_dtypes, wire_advisory=True)
        return jax.tree_util.tree_unflatten(treedef, out)
    if axis_name is None:
        axis_name = _mesh.data_axes() or ("data",)

    def _reduce_buffer(buf):
        return _cops.allreduce(buf, axis_name=axis_name, op=op,
                               compression=compression)

    return fuse_apply(grads, _reduce_buffer, fusion_threshold_bytes)


class DistributedOptimizer:
    """Wrap an optax ``GradientTransformation`` so that ``update`` averages
    gradients across the mesh before applying the inner optimizer.

    Reference parity: ``hvd.DistributedOptimizer`` (tensorflow/__init__.py:
    135-209).  Implements the optax interface, so it drops into any optax
    pipeline (including ``optax.chain``) and into flax's TrainState.

    Must be called inside a context with the mesh axes bound (shard_map or
    pmap); under plain pjit-with-sharded-batch XLA already inserts the psum,
    in which case wrap with ``reduce_gradients=False`` to keep only the
    bookkeeping.

    ``local_sgd_steps=H`` (default: ``HOROVOD_LOCAL_SGD_STEPS``, 1)
    switches the host-driven (eager/DCN) path to communication-relaxed
    local SGD: ``update`` applies gradients purely LOCALLY (no per-step
    allreduce), and the attached :class:`horovod_tpu.elastic.LocalSGD`
    policy syncs the model delta every ``H`` steps — the training loop
    calls ``params = opt.local_sgd.maybe_sync(params)`` after
    ``optax.apply_updates``.  ``H <= 1`` is byte-identical to the plain
    synchronous path (the policy is not even constructed).  The outer
    delta sync is epoch-stamped: an elastic resize re-anchors instead of
    leaking a dead incarnation's delta, and it composes unchanged with
    wire compression and backup-worker partial commits.  With a
    ``Compression.topk(ratio)`` compression, the outer sync itself ships
    the model DELTA through the top-k sparse path with its own
    epoch-stamped error-feedback residuals (docs/elastic.md).

    ``sharded=True`` (default: ``HOROVOD_SHARDED``) turns the host-driven
    path into a ZeRO-1 sharded optimizer: gradients are flattened into
    ONE fp32 vector, reduced by ``reducescatter`` (half an allreduce's
    wire bytes), the inner optax transformation keeps state ONLY for this
    rank's shard (~1/N of the optimizer memory), and the shard's updates
    ride back on ``allgather``.  Elementwise inner optimizers (sgd,
    momentum, adam, adamw) make the step BIT-IDENTICAL to the equivalent
    unsharded flat step — asserted per dtype in tests.  Host path only
    (inside jit use the fsdp mesh axis instead); fp32 params only; see
    docs/zero.md for the memory math and resize semantics.

    ``fsdp=True`` (default: ``HOROVOD_FSDP``) climbs one more rung of the
    sharding ladder (ZeRO-3/FSDP): the model is cut into per-layer
    UNITS — one per top-level key of the param tree, or explicit groups
    via ``fsdp_units=[["embed", "lm_head"], ...]`` — and each unit gets
    its own :class:`~horovod_tpu.runtime.fsdp.FsdpPlane` window.
    ``update`` enqueues every unit's gradient reducescatter up front in
    reverse unit order with priority band = unit index (the backward
    cascade: early-forward units land in urgent bands because the next
    step needs them first), runs each unit's inner update on the owned
    shard as its reduction drains, and pipelines the per-unit update
    allgathers at band 0 so they overlap later units' shard updates.
    Inner optimizer state is per-unit shard-sized (the same ~1/N as
    ZeRO-1), the step stays bit-identical to the unsharded anchor, and
    the optax interface is unchanged (full ``updates`` tree out).  Full
    1/N *parameter* residency — gather/free around each layer's
    compute — is the plane's own API
    (:meth:`horovod_tpu.runtime.fsdp.FsdpPlane.gather`); a tree-in/
    tree-out optax wrapper cannot free params it does not own, and
    docs/zero.md is honest about that line.
    """

    def __init__(self, optimizer, *, axis_name=None, op=Average,
                 compression=Compression.none, fusion_threshold_bytes=None,
                 reduce_gradients=True, name=None, local_sgd_steps=None,
                 sharded=None, fsdp=None, fsdp_units=None,
                 fsdp_prefetch=None):
        from horovod_tpu.elastic.state import (LocalSGD,
                                               default_local_sgd_steps)
        from horovod_tpu.runtime.fsdp import fsdp_default
        from horovod_tpu.runtime.sharded import sharded_default

        self._inner = optimizer
        self._axis_name = axis_name
        self._op = op
        self._compression = compression
        self._fusion_threshold = fusion_threshold_bytes
        self._reduce = reduce_gradients
        self.name = name or "DistributedOptimizer"
        self._local_sgd_steps = (default_local_sgd_steps()
                                 if local_sgd_steps is None
                                 else max(1, int(local_sgd_steps)))
        self._sharded = (sharded_default() if sharded is None
                         else bool(sharded))
        self._fsdp = fsdp_default() if fsdp is None else bool(fsdp)
        if self._fsdp and self._sharded:
            raise ValueError(
                "fsdp=True and sharded=True are mutually exclusive: "
                "FSDP subsumes the ZeRO-1 step (pick one rung of the "
                "ladder; see docs/zero.md)")
        if (self._sharded or self._fsdp) and self._local_sgd_steps > 1:
            raise ValueError(
                "sharded/fsdp and local_sgd_steps>1 are mutually "
                "exclusive: local SGD skips the per-step reduction the "
                "sharded step is built around")
        if (self._sharded or self._fsdp) and not reduce_gradients:
            raise ValueError(
                "sharded/fsdp requires reduce_gradients=True: the ZeRO "
                "step IS the reduction (reducescatter -> shard update "
                "-> allgather); without it the shard-sized state cannot "
                "apply and ranks would silently diverge")
        if (self._sharded or self._fsdp) and op not in (Average, Sum):
            raise ValueError(
                "sharded/fsdp reduces gradients with SUM/AVERAGE only")
        #: Lazy ZeRO state (built on first init() from the param tree).
        self._sharder = None
        self._tree_shapes = None
        #: Lazy FSDP state (unit planes built on first init()).
        self._fsdp_plane = None
        self._fsdp_groups = None
        self._fsdp_unit_spec = fsdp_units
        self._fsdp_prefetch = fsdp_prefetch
        #: The periodic-sync policy (None when H <= 1 — fully
        #: synchronous, the pre-local-SGD contract, byte-identical).
        self.local_sgd = (LocalSGD(self._local_sgd_steps,
                                   compression=compression)
                          if self._local_sgd_steps > 1 else None)

    @property
    def inner(self):
        """The wrapped optax transformation."""
        return self._inner

    def with_axis_name(self, axis_name):
        """A copy bound to ``axis_name`` (used by train-step builders to pin
        reduction to the mesh they run on)."""
        copy = DistributedOptimizer(
            self._inner, axis_name=axis_name, op=self._op,
            compression=self._compression,
            fusion_threshold_bytes=self._fusion_threshold,
            reduce_gradients=self._reduce, name=self.name,
            local_sgd_steps=self._local_sgd_steps,
            sharded=self._sharded, fsdp=self._fsdp,
            fsdp_units=self._fsdp_unit_spec,
            fsdp_prefetch=self._fsdp_prefetch,
        )
        # Share the policy/sharder instances: anchors and counters live
        # with the training run, not with any one bound copy.
        copy.local_sgd = self.local_sgd
        copy._sharder = self._sharder
        copy._tree_shapes = self._tree_shapes
        copy._fsdp_plane = self._fsdp_plane
        copy._fsdp_groups = self._fsdp_groups
        return copy

    def init(self, params):
        if self._fsdp:
            return self._fsdp_init(params)
        if not self._sharded:
            return self._inner.init(params)
        return self._sharded_init(params)

    def update(self, grads, state, params=None, **extra):
        # FSDP path: per-unit RS cascade → shard updates → banded AGs.
        if self._fsdp and self._reduce:
            return self._fsdp_update(grads, state, params, **extra)
        # ZeRO path: RS(flat grads) → shard-local inner update → AG.
        if self._sharded and self._reduce:
            return self._sharded_update(grads, state, params, **extra)
        # Local-SGD phase: gradients apply purely locally; the policy's
        # maybe_sync (called by the training loop on the params) is the
        # only wire traffic — H× fewer syncs by construction.
        if self._reduce and self._local_sgd_steps <= 1:
            grads = allreduce_gradients(
                grads,
                axis_name=self._axis_name,
                op=self._op,
                compression=self._compression,
                fusion_threshold_bytes=self._fusion_threshold,
            )
        return self._inner.update(grads, state, params, **extra)

    # -- ZeRO-1 sharded path (host-driven; see docs/zero.md) --

    def _sharded_init(self, params):
        import numpy as np
        import jax.numpy as jnp
        from horovod_tpu.ops.compression import TopKCompressor
        from horovod_tpu.runtime.sharded import FlatSharder

        if isinstance(self._compression, TopKCompressor):
            raise ValueError(
                "sharded=True reduces gradients with reducescatter; the "
                "top-k sparse path has no scatter half — use a wire "
                "compressor (Compression.wire_bf16 etc.) instead")
        leaves = jax.tree.leaves(params)
        for leaf in leaves:
            if jnp.asarray(leaf).dtype != jnp.float32:
                raise TypeError(
                    "sharded=True requires float32 params (the fp32-"
                    "master-weight mixed-precision variant lives in the "
                    "torch sharded optimizer; see docs/zero.md) — got "
                    f"{jnp.asarray(leaf).dtype}")
        shapes = [tuple(np.shape(leaf)) for leaf in leaves]
        n = int(sum(int(np.prod(s)) if s else 1 for s in shapes))
        self._tree_shapes = shapes
        self._sharder = FlatSharder(n, np.float32, name=self.name)
        shard = FlatSharder.slice_flat(
            [np.asarray(leaf) for leaf in leaves],
            self._sharder.offset, self._sharder.count, np.float32)
        # The inner transformation sees ONLY the owned shard: its state
        # (momenta etc.) is ~1/N of the unsharded footprint, which is
        # the whole point.
        return self._inner.init(jnp.asarray(shard))

    def _sharded_update(self, grads, state, params=None, **extra):
        import numpy as np
        import jax.numpy as jnp
        from horovod_tpu.runtime.sharded import FlatSharder

        leaves, treedef = jax.tree.flatten(grads)
        if leaves and _is_traced(leaves[0]):
            raise RuntimeError(
                "sharded=True is the host-driven (eager/DCN) path; "
                "inside jit shard optimizer state with the mesh's "
                "'fsdp' axis instead (parallel/mesh.py)")
        if self._sharder is None:
            raise RuntimeError(
                "sharded DistributedOptimizer.update() before init(): "
                "the shard layout is anchored at init(params)")
        flat_g = FlatSharder.flatten(
            [np.asarray(leaf) for leaf in leaves], np.float32)
        sh = self._sharder
        # Params: slice ONLY the owned window out of the virtual concat
        # (a full flat copy of the model every step would reintroduce
        # the O(N) host buffer sharding exists to avoid).
        p_shard = None
        if params is not None:
            p_shard = FlatSharder.slice_flat(
                [np.asarray(leaf) for leaf in jax.tree.leaves(params)],
                sh.offset, sh.count, np.float32)
        box = {}

        def local_update(shard_g):
            sp = jnp.asarray(p_shard) if p_shard is not None else None
            upd, box["state"] = self._inner.update(
                jnp.asarray(shard_g), state, sp, **extra)
            return np.asarray(upd, dtype=np.float32)

        wire = getattr(self._compression, "engine_wire_dtype", None)
        wire = wire if wire in ("fp16", "bf16", "int8", "fp8") else None
        full = sh.step(flat_g, local_update,
                       average=(self._op is Average), wire_dtype=wire)
        outs = FlatSharder.unflatten(full, self._tree_shapes)
        updates = jax.tree_util.tree_unflatten(
            treedef, [jnp.asarray(o) for o in outs])
        return updates, box["state"]

    # -- ZeRO-3/FSDP path (host-driven; see docs/zero.md) --

    def _fsdp_init(self, params):
        import numpy as np
        import jax.numpy as jnp
        from horovod_tpu.ops.compression import TopKCompressor
        from horovod_tpu.runtime.fsdp import FsdpPlane

        if isinstance(self._compression, TopKCompressor):
            raise ValueError(
                "fsdp=True reduces gradients with reducescatter; the "
                "top-k sparse path has no scatter half — use a wire "
                "compressor (Compression.wire_bf16 etc.) instead")
        leaves = jax.tree.leaves(params)
        for leaf in leaves:
            if jnp.asarray(leaf).dtype != jnp.float32:
                raise TypeError(
                    "fsdp=True requires float32 params (the fp32-master "
                    "mixed-precision variant lives in the torch FSDP "
                    "optimizer; see docs/zero.md) — got "
                    f"{jnp.asarray(leaf).dtype}")
        self._fsdp_groups = _fsdp_unit_groups(params,
                                              self._fsdp_unit_spec)
        wire = getattr(self._compression, "engine_wire_dtype", None)
        wire = wire if wire in ("fp16", "bf16", "int8", "fp8") else None
        np_leaves = [np.asarray(leaf) for leaf in leaves]
        self._fsdp_plane = FsdpPlane(
            [[np_leaves[j] for j in idxs]
             for _, idxs in self._fsdp_groups],
            name=self.name, prefetch=self._fsdp_prefetch,
            wire_dtype=wire, average=(self._op is Average))
        # Per-unit inner states, each shard-sized: the whole optimizer
        # footprint is ~1/N like ZeRO-1, but reductions/gathers are now
        # per-unit so the banded scheduler can overlap them.
        return tuple(self._inner.init(jnp.asarray(self._fsdp_plane.shard(i)))
                     for i in range(self._fsdp_plane.n_units))

    def _fsdp_update(self, grads, state, params=None, **extra):
        import numpy as np
        import jax.numpy as jnp
        from horovod_tpu.runtime import engine_or_none
        from horovod_tpu.runtime.fsdp import _note_prefetch
        from horovod_tpu.runtime.sharded import FlatSharder

        leaves, treedef = jax.tree.flatten(grads)
        if leaves and _is_traced(leaves[0]):
            raise RuntimeError(
                "fsdp=True is the host-driven (eager/DCN) path; inside "
                "jit shard params with the mesh's 'fsdp' axis instead "
                "(parallel/mesh.py)")
        plane = self._fsdp_plane
        if plane is None:
            raise RuntimeError(
                "fsdp DistributedOptimizer.update() before init(): the "
                "unit layout is anchored at init(params)")
        p_leaves = ([np.asarray(leaf) for leaf in jax.tree.leaves(params)]
                    if params is not None else None)
        g_leaves = [np.asarray(leaf) for leaf in leaves]
        eng = engine_or_none()
        new_states = [None] * plane.n_units
        unit_updates = [None] * plane.n_units
        ag_handles = {}
        try:
            # Backward cascade: enqueue EVERY unit's reducescatter up
            # front, last unit first (its grads finish first in a real
            # vjp), priority band = unit index so the units the next
            # forward needs first win the wire.
            for i in reversed(range(plane.n_units)):
                _, idxs = self._fsdp_groups[i]
                plane.reduce_grads(i, [g_leaves[j] for j in idxs])
            for i in range(plane.n_units):
                u = plane.units[i]
                g_shard = plane.wait_grads(i)
                p_shard = None
                if p_leaves is not None:
                    _, idxs = self._fsdp_groups[i]
                    p_shard = jnp.asarray(FlatSharder.slice_flat(
                        [p_leaves[j] for j in idxs],
                        u.sharder.offset, u.sharder.count, np.float32))
                upd, new_states[i] = self._inner.update(
                    jnp.asarray(g_shard), state[i], p_shard, **extra)
                upd = np.asarray(upd, dtype=np.float32)
                if eng is None:
                    unit_updates[i] = upd
                else:
                    # Band-0 update allgather: in flight while LATER
                    # units' reductions drain and shards update.
                    ag_handles[i] = eng.enqueue_allgather(
                        upd, name=f"{plane._wire_name}.u{i}.agu",
                        priority=0)
            for i in sorted(ag_handles):
                # Overlap accounting: the gather was free iff it landed
                # before this drain reached it.
                _note_prefetch(eng.poll(ag_handles[i]))
                unit_updates[i] = np.asarray(
                    eng.synchronize(ag_handles.pop(i)))
        except BaseException:
            # Drain hygiene: never strand a handle (StepSkipped on one
            # unit must not leave the others' buffers in flight).
            plane.drain()
            for i in list(ag_handles):
                try:
                    eng.synchronize(ag_handles.pop(i))
                except BaseException:
                    pass
            raise
        out_leaves = [None] * len(leaves)
        for i, (_, idxs) in enumerate(self._fsdp_groups):
            u = plane.units[i]
            outs = FlatSharder.unflatten(unit_updates[i], u.shapes)
            for j, o in zip(idxs, outs):
                out_leaves[j] = jnp.asarray(o)
        plane.step()
        updates = jax.tree_util.tree_unflatten(treedef, out_leaves)
        return updates, tuple(new_states)

    # Make it quack like an optax.GradientTransformation namedtuple.
    def __iter__(self):
        return iter((self.init, self.update))


def _fsdp_unit_groups(params, fsdp_units=None):
    """FSDP unit boundaries from the param tree's TOP-LEVEL structure:
    ``[(unit_name, [global leaf indices])]`` in jax flatten order.  A
    dict tree gets one unit per key (jax flattens dicts key-sorted); a
    list/tuple one per element; anything else is a single unit.
    ``fsdp_units=[["embed", "lm_head"], ["blocks"]]`` overrides with
    explicit key groups — every top-level key exactly once (tied layers
    that must share a window, or tiny layers worth coalescing)."""
    if isinstance(params, dict):
        try:
            keys = sorted(params)
        except TypeError as e:
            raise TypeError(
                "fsdp=True needs sortable top-level dict keys (jax's own "
                "dict flatten order)") from e
        spans, off = {}, 0
        for k in keys:
            cnt = len(jax.tree_util.tree_leaves(params[k]))
            spans[k] = list(range(off, off + cnt))
            off += cnt
        if fsdp_units is not None:
            groups, seen = [], set()
            for gi, group in enumerate(fsdp_units):
                idxs = []
                for k in group:
                    if k not in spans:
                        raise ValueError(
                            f"fsdp_units names unknown top-level key "
                            f"{k!r} (have {sorted(map(str, keys))})")
                    if k in seen:
                        raise ValueError(
                            f"fsdp_units lists key {k!r} twice")
                    seen.add(k)
                    idxs.extend(spans[k])
                if idxs:
                    groups.append(("+".join(map(str, group)), idxs))
            missing = [str(k) for k in keys if k not in seen and spans[k]]
            if missing:
                raise ValueError(
                    f"fsdp_units must cover every top-level key; "
                    f"missing {missing}")
            return groups
        return [(str(k), spans[k]) for k in keys if spans[k]]
    if isinstance(params, (list, tuple)):
        groups, off = [], 0
        for i, sub in enumerate(params):
            cnt = len(jax.tree_util.tree_leaves(sub))
            if cnt:
                groups.append((str(i), list(range(off, off + cnt))))
            off += cnt
        if fsdp_units is not None:
            raise ValueError(
                "fsdp_units grouping needs a dict param tree")
        return groups
    n = len(jax.tree_util.tree_leaves(params))
    return [("all", list(range(n)))]


def broadcast_parameters(params, root_rank=0, *, axis_name=None):
    """Return ``params`` with every leaf replaced by root's value.

    Reference parity: ``broadcast_global_variables`` / torch
    ``broadcast_parameters`` (tensorflow/__init__.py:90-98,
    torch/__init__.py:153-182).  Functional: returns the synced pytree.

    On tracers this is an in-jit masked-psum broadcast; on concrete arrays it
    is a cross-process broadcast through the runtime (host path), which at
    ``size()==1`` is the identity.
    """
    leaves = jax.tree.leaves(params)
    if leaves and _is_traced(leaves[0]):
        if axis_name is None:
            axis_name = _mesh.data_axes() or ("data",)

        def _bcast_buffer(buf):
            return _cops.broadcast(buf, root_rank, axis_name=axis_name)

        return fuse_apply(params, _bcast_buffer)
    from horovod_tpu.runtime import eager

    return jax.tree.map(
        lambda x: eager.broadcast(x, root_rank=root_rank), params
    )


def broadcast_optimizer_state(opt_state, root_rank=0, *, axis_name=None):
    """Broadcast optimizer state from root (reference torch/__init__.py:
    185-301).  Optax states are pytrees of arrays, so no scalar
    tensor-ization dance is needed — one fused broadcast covers it."""
    return broadcast_parameters(opt_state, root_rank, axis_name=axis_name)


# ---------------------------------------------------------------------------
# Train-step builder (the minimum end-to-end slice, SURVEY.md §7 step 4)
# ---------------------------------------------------------------------------

def make_train_step(loss_fn: Callable, optimizer, mesh: Optional[Mesh] = None,
                    *, donate=True, has_aux=False):
    """Build a jitted SPMD train step: shard batch over data axes, compute
    grads, fused-allreduce them, apply the optimizer.

    ``loss_fn(params, batch) -> scalar loss``, or with ``has_aux=True``
    ``loss_fn(params, aux_state, batch) -> (loss, new_aux_state)`` where
    ``aux_state`` is non-differentiated model state (e.g. batch-norm
    statistics), averaged across the data axes each step (cross-replica
    batch norm).  ``optimizer`` may be a plain optax transformation (it will
    be wrapped) or a ``DistributedOptimizer``.

    Returns ``step(params, opt_state, batch) -> (params, opt_state, loss)``
    (with ``has_aux``: ``step(params, opt_state, aux_state, batch) ->
    (params, opt_state, aux_state, loss)``); params/opt_state replicated,
    batch sharded on the data axes.
    """
    mesh = mesh or default_mesh()
    axes = _mesh.data_axes(mesh) or mesh.axis_names
    if not isinstance(optimizer, DistributedOptimizer):
        optimizer = DistributedOptimizer(optimizer, axis_name=axes)
    elif optimizer._axis_name is None:
        # Bind reduction to THIS mesh's data-like axes — resolving from the
        # thread-local default mesh would silently skip e.g. 'fsdp'.
        optimizer = optimizer.with_axis_name(axes)

    import optax

    def _sharded_step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        loss = _cops.allreduce(loss, axis_name=axes, op=Average)
        return params, opt_state, loss

    def _sharded_step_aux(params, opt_state, aux_state, batch):
        (loss, aux_state), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params, aux_state, batch)
        aux_state = jax.tree.map(
            lambda x: _cops.allreduce(x, axis_name=axes, op=Average)
            if _is_inexact(x) else x,
            aux_state,
        )
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        loss = _cops.allreduce(loss, axis_name=axes, op=Average)
        return params, opt_state, aux_state, loss

    batch_spec = PartitionSpec(axes)
    replicated = PartitionSpec()
    n_state = 3 if has_aux else 2
    # check_vma=False because this step implements the Horovod pattern —
    # an EXPLICIT grad psum in DistributedOptimizer.update — whereas
    # VMA-aware AD would itself psum the cotangents of the replicated
    # params (double-reduction).  pipeline_apply composes with this
    # builder: its broadcast-from-last-stage pins its own vjp, so it
    # differentiates identically with VMA checking on or off
    # (parallel/pipeline.py).
    step = shard_map(
        _sharded_step_aux if has_aux else _sharded_step,
        mesh=mesh,
        in_specs=(replicated,) * n_state + (batch_spec,),
        out_specs=(replicated,) * n_state + (replicated,),
        check_vma=False,
    )
    donate_args = tuple(range(n_state)) if donate else ()
    return jax.jit(step, donate_argnums=donate_args)


def _is_inexact(x) -> bool:
    import jax.numpy as jnp

    return jnp.issubdtype(jnp.asarray(x).dtype, jnp.inexact)
