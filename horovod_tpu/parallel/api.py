"""High-level parallel-training API: parameter sharding + pjit train steps.

This is the GSPMD path of the framework: parameters and batch get
``NamedSharding`` annotations, everything runs under one ``jax.jit``, and XLA
inserts the ICI collectives (gradient reductions, weight all-gathers for
fsdp, activation collectives for tensor parallelism).  The explicit-collective
path (``shard_map`` + ``lax.psum`` through ``DistributedOptimizer``) lives in
``horovod_tpu.jax.make_train_step``; both are first-class.

Reference parity note: the reference has *only* data parallelism
(SURVEY.md §2.3) — its DistributedOptimizer allreduces gradients.  Here the
same user-visible contract ("wrap your optimizer, gradients arrive reduced")
extends across data/fsdp/tensor/expert axes because reduction placement is
derived from the shardings rather than hard-coded.
"""

from __future__ import annotations

import re
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from horovod_tpu.ops.losses import softmax_cross_entropy

__all__ = [
    "SHARDING_RULES",
    "infer_param_spec",
    "shard_params",
    "make_parallel_train_step",
    "lm_loss_fn",
]

# Path-regex → axis names per dimension (None = replicate that dim).
# Megatron-style placement: attention/MLP input projections are
# column-parallel (output dim on ``tensor``), output projections are
# row-parallel (input dim on ``tensor``); everything big also shards one dim
# over ``fsdp``; MoE expert-batched weights shard the expert dim.
SHARDING_RULES: tuple[tuple[str, tuple[Optional[str], ...]], ...] = (
    (r"tok_emb.*embedding$", ("tensor", "fsdp")),
    (r"(pos_emb|type_emb).*embedding$", (None, "fsdp")),
    (r"(wq|wk|wv|qkv|mlp_in|w_gate_up|mlm_transform)/kernel$", ("fsdp", "tensor")),
    (r"(wo|proj|w_down|mlp_out)/kernel$", ("tensor", "fsdp")),
    (r"(lm_head|mlm_out)/kernel$", ("fsdp", "tensor")),
    (r"moe/w_gate_up$", ("expert", "fsdp", "tensor")),
    (r"moe/w_down$", ("expert", "tensor", "fsdp")),
    (r"router/kernel$", ("fsdp", None)),
    (r"head/kernel$", ("fsdp", "tensor")),   # resnet classifier
    (r"kernel$", (None, None, None, "tensor")),  # convs: shard out-channels
)


def _path_str(path) -> str:
    parts = []
    for k in path:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "name"):
            parts.append(str(k.name))
        else:
            parts.append(str(k))
    return "/".join(parts)


def infer_param_spec(path: str, shape: tuple[int, ...], mesh: Mesh,
                     rules=SHARDING_RULES) -> P:
    """PartitionSpec for one parameter.

    Axes not present in the mesh, mesh axes of size 1, and axes that do not
    divide the corresponding dimension are dropped (replicated) — so the same
    rules work on any mesh shape, including single-axis data-parallel meshes.
    """
    for pattern, dims in rules:
        if re.search(pattern, path):
            if len(dims) != len(shape):
                continue
            spec = []
            for dim_size, axis in zip(shape, dims):
                if (axis is None or axis not in mesh.axis_names
                        or mesh.shape[axis] == 1
                        or dim_size % mesh.shape[axis] != 0):
                    spec.append(None)
                else:
                    spec.append(axis)
            return P(*spec)
    return P()  # replicate by default (norms, biases, small tables)


def shard_params(params, mesh: Mesh, rules=SHARDING_RULES):
    """Device-put every parameter with its inferred NamedSharding."""

    def _place(path, leaf):
        spec = infer_param_spec(_path_str(path), jnp.shape(leaf), mesh, rules)
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map_with_path(_place, params)


def param_shardings(params, mesh: Mesh, rules=SHARDING_RULES):
    """The NamedSharding pytree matching ``shard_params`` placement."""

    def _spec(path, leaf):
        return NamedSharding(
            mesh, infer_param_spec(_path_str(path), jnp.shape(leaf), mesh, rules)
        )

    return jax.tree_util.tree_map_with_path(_spec, params)


def lm_loss_fn(model) -> Callable:
    """Next-token cross-entropy on ``tokens`` [B, S+1]."""

    def loss_fn(params, tokens):
        inputs, targets = tokens[:, :-1], tokens[:, 1:]
        logits = model.apply(params, inputs)
        # lse-form CE (ops/losses.py): no [B,S,V] fp32 log-prob tensor.
        return softmax_cross_entropy(logits, targets)

    return loss_fn


def _batch_axes(mesh: Mesh) -> tuple[str, ...]:
    """Mesh axes the batch dimension is sharded over.  ``fsdp`` is a batch
    axis (ZeRO data parallelism shards state, not the batch semantics)."""
    from horovod_tpu.parallel.mesh import data_axes

    return data_axes(mesh)


def make_parallel_train_step(model, optimizer, mesh: Mesh, *,
                             loss_fn: Optional[Callable] = None,
                             rules=SHARDING_RULES,
                             donate: bool = True):
    """Build a jitted GSPMD train step over ``mesh``.

    ``step(params, opt_state, tokens) -> (params, opt_state, loss)`` with
    params sharded per ``rules``, batch sharded over the data-like axes, and
    XLA inserting all collectives (this is the pjit path; DistributedOptimizer
    instances are switched to ``reduce_gradients=False`` because GSPMD already
    reduces gradients — the psum the reference does by hand,
    tensorflow/__init__.py:183-209).
    """
    loss_fn = loss_fn or lm_loss_fn(model)

    from horovod_tpu.jax import DistributedOptimizer

    if isinstance(optimizer, DistributedOptimizer):
        inner = optimizer.inner
    else:
        inner = optimizer

    import optax

    def step(params, opt_state, tokens):
        tokens = jax.lax.with_sharding_constraint(
            tokens, NamedSharding(mesh, P(_batch_axes(mesh) or None))
        )
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens)
        updates, opt_state = inner.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    donate_args = (0, 1) if donate else ()
    return jax.jit(step, donate_argnums=donate_args)
