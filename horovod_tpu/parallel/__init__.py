"""Parallelism strategies: meshes, FSDP, sequence/context parallelism."""

from horovod_tpu.parallel.mesh import (
    AXIS_DATA,
    AXIS_EXPERT,
    AXIS_FSDP,
    AXIS_SEQ,
    AXIS_TENSOR,
    build_mesh,
    data_axes,
    data_parallel_mesh,
    default_mesh,
    mesh_axis_size,
    set_default_mesh,
    use_mesh,
)

__all__ = [
    "AXIS_DATA",
    "AXIS_EXPERT",
    "AXIS_FSDP",
    "AXIS_SEQ",
    "AXIS_TENSOR",
    "build_mesh",
    "data_axes",
    "data_parallel_mesh",
    "default_mesh",
    "mesh_axis_size",
    "set_default_mesh",
    "use_mesh",
]
