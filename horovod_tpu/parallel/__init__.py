"""Parallelism strategies: meshes, FSDP, sequence/context parallelism."""

from horovod_tpu.parallel.mesh import (
    AXIS_DATA,
    AXIS_EXPERT,
    AXIS_FSDP,
    AXIS_SEQ,
    AXIS_TENSOR,
    build_mesh,
    data_axes,
    data_parallel_mesh,
    default_mesh,
    mesh_axis_size,
    set_default_mesh,
    use_mesh,
)

__all__ = [
    "AXIS_DATA",
    "AXIS_EXPERT",
    "AXIS_FSDP",
    "AXIS_SEQ",
    "AXIS_TENSOR",
    "build_mesh",
    "data_axes",
    "data_parallel_mesh",
    "default_mesh",
    "mesh_axis_size",
    "set_default_mesh",
    "use_mesh",
]

from horovod_tpu.parallel.api import (
    SHARDING_RULES,
    infer_param_spec,
    lm_loss_fn,
    make_parallel_train_step,
    param_shardings,
    shard_params,
)
from horovod_tpu.parallel.pipeline import (
    init_pipelined_llama,
    make_pipelined_llama_train_step,
    pipeline_apply,
    stack_pytrees,
    unstack_pytree,
)
from horovod_tpu.parallel.ring_attention import (
    make_ring_attention_fn,
    ring_attention,
    ulysses_attention,
)
from horovod_tpu.parallel.seq import make_context_parallel_train_step

__all__ += [
    "SHARDING_RULES", "infer_param_spec", "lm_loss_fn",
    "make_parallel_train_step", "param_shardings", "shard_params",
    "init_pipelined_llama", "make_pipelined_llama_train_step",
    "pipeline_apply", "stack_pytrees", "unstack_pytree",
    "make_ring_attention_fn", "ring_attention", "ulysses_attention",
    "make_context_parallel_train_step",
]
