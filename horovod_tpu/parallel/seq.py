"""Context-parallel (sequence-parallel) LM training.

Builds a shard_map train step where the *sequence* dimension is sharded
over the ``seq`` mesh axis and attention runs as ring attention
(``horovod_tpu.parallel.ring_attention``), composing with data parallelism
on the batch axes.  This is the long-context training path: activation
memory per chip scales as S/seq_size, KV blocks ride nearest-neighbor ICI.

No reference equivalent (SURVEY.md §5.7: the reference predates sequence
parallelism); TPU-native new work.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from horovod_tpu.common.jax_compat import shard_map

from horovod_tpu.models.llama import LlamaConfig, LlamaModel
from horovod_tpu.ops.losses import softmax_cross_entropy
from horovod_tpu.parallel.ring_attention import make_ring_attention_fn

__all__ = ["make_context_parallel_train_step"]


def make_context_parallel_train_step(cfg: LlamaConfig, optimizer,
                                     mesh: Mesh, *,
                                     seq_axis: str = "seq",
                                     attention: str = "auto",
                                     donate: bool = True):
    """Jitted LM train step with sequence sharded over ``seq_axis`` and
    batch sharded over the data-like axes.

    ``step(params, opt_state, inputs, targets) ->
    (params, opt_state, loss)`` where inputs/targets are [B, S] token ids
    (S divisible by the seq-axis size, B by the data axes' product).
    ``attention``: "ulysses" (all-to-all head scatter; the local
    full-sequence attention runs the Pallas flash kernel — shard_map
    bodies are Manual-mesh, so it lowers legally), "ring" (blockwise
    ppermute ring; scales sequence past what one chip's heads allow), or
    "auto" (default): ulysses whenever the head counts divide the
    ``seq_axis`` size — the flash-backed path — ring otherwise.
    """
    import optax

    from horovod_tpu.jax import DistributedOptimizer
    from horovod_tpu.parallel.mesh import data_axes
    from horovod_tpu.parallel.ring_attention import ulysses_attention

    if attention == "auto":
        seq_size = mesh.shape[seq_axis]
        heads_divide = (cfg.num_heads % seq_size == 0
                        and cfg.num_kv_heads % seq_size == 0)
        attention = "ulysses" if heads_divide else "ring"
    if attention == "ring":
        attention_fn = make_ring_attention_fn(seq_axis)
    elif attention == "ulysses":
        def attention_fn(q, k, v, *a, **kw):
            return ulysses_attention(q, k, v, axis_name=seq_axis)
    else:
        raise ValueError(f"unknown attention {attention!r}")

    model = LlamaModel(cfg, attention_fn=attention_fn)
    batch_axes = data_axes(mesh) or ()
    reduce_axes = tuple(batch_axes) + (seq_axis,)

    from horovod_tpu.ops.collective_ops import Sum

    # Per-shard gradients are partial SUMS of the global token mean (each
    # shard holds different tokens), so the cross-shard reduction must be
    # SUM, not average.
    inner = optimizer.inner if isinstance(optimizer, DistributedOptimizer) \
        else optimizer
    optimizer = DistributedOptimizer(inner, axis_name=reduce_axes, op=Sum)

    def _local_loss(params, inputs, targets):
        offset = lax.axis_index(seq_axis) * inputs.shape[1]
        logits = model.apply(params, inputs, positions_offset=offset)
        # Local *sum* in lse form (no fp32 log-prob tensor); the mean
        # denominator is the global token count so the psum over
        # data+seq axes reconstructs the global mean.
        return softmax_cross_entropy(logits, targets, reduction="sum")

    def _step(params, opt_state, inputs, targets):
        n_global = (inputs.shape[0] * lax.axis_size(batch_axes)
                    if batch_axes else inputs.shape[0])
        s_global = inputs.shape[1] * lax.axis_size(seq_axis)
        denom = n_global * s_global
        loss_sum, grads = jax.value_and_grad(_local_loss)(
            params, inputs, targets)
        grads = jax.tree.map(lambda g: g / denom, grads)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        loss = lax.psum(loss_sum, reduce_axes) / denom
        return params, opt_state, loss

    batch_spec = P(tuple(batch_axes) if batch_axes else None, seq_axis)
    step = shard_map(
        _step,
        mesh=mesh,
        in_specs=(P(), P(), batch_spec, batch_spec),
        out_specs=(P(), P(), P()),
        check_vma=False,
    )
    donate_args = (0, 1) if donate else ()
    return jax.jit(step, donate_argnums=donate_args)
