"""Pipeline parallelism: GPipe-style microbatched stages over a mesh axis.

No reference equivalent (Horovod 0.15.1 is data-parallel only, SURVEY.md
§2.3); pipeline support is TPU-native new work.  Design:

* the layer stack is split into ``pipe`` contiguous stages; stage
  parameters live stacked with a leading stage dim sharded over the
  ``pipe`` mesh axis — each device materializes only its own stage (the
  memory win that motivates PP);
* inside ``shard_map`` the batch is cut into microbatches that flow
  through the stage ring via ``lax.ppermute`` — neighbor-only ICI
  transfers;
* every device runs the identical SPMD program (XLA requirement): during
  bubble steps stages compute on garbage that is masked out of the result;
* backward is plain ``jax.grad`` — ppermute's transpose reverses the ring,
  so autodiff yields the reverse-schedule pipeline automatically (GPipe
  semantics: all microbatch activations live until backward; wrap
  ``stage_fn`` in ``jax.checkpoint`` to trade FLOPs for memory).

The final broadcast-from-last-stage pins its own vjp
(``_broadcast_from_last``): relying on AD's psum transpose there is
version-sensitive — the check_rep jax line conservatively sums the
replicated cotangents (inflating every stage gradient by the stage
count), the VMA line transposes correctly — so the rule is written by
hand and ``pipeline_apply`` differentiates identically under
``check_vma=True`` AND ``check_vma=False`` on both lines (verified
against sequential-execution gradients in tests/test_pipeline.py).
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from horovod_tpu.common.jax_compat import shard_map

from horovod_tpu.ops.losses import softmax_cross_entropy

__all__ = [
    "pipeline_apply",
    "stack_pytrees",
    "unstack_pytree",
    "init_pipelined_llama",
    "make_pipelined_llama_train_step",
]


def stack_pytrees(trees: Sequence):
    """Stack a list of identical-structure pytrees along a new leading axis
    (layer params -> scannable/shardable stacked params)."""
    return jax.tree.map(lambda *xs: jnp.stack(xs, axis=0), *trees)


def unstack_pytree(tree, n: int):
    return [jax.tree.map(lambda x: x[i], tree) for i in range(n)]


# Broadcast-from-last-stage with an EXPLICIT vjp.  The forward is the
# masked psum; the correct cotangent is simply the (replicated) output
# cotangent delivered to the last stage and zero elsewhere.  Relying on
# AD's psum transpose here is version-sensitive — jax's shard_map AD
# changed the replicated-cotangent convention between the check_rep line
# (0.4.x: transpose sums the replicas, inflating every stage gradient by
# the stage count) and the VMA line — so the rule is pinned by hand.
from functools import partial as _partial  # noqa: E402


@_partial(jax.custom_vjp, nondiff_argnums=(2,))
def _broadcast_from_last(outputs, mask, axis_name):
    return lax.psum(outputs * mask, axis_name)


def _broadcast_from_last_fwd(outputs, mask, axis_name):
    return _broadcast_from_last(outputs, mask, axis_name), mask


def _broadcast_from_last_bwd(axis_name, mask, g):
    return (g * mask, jnp.zeros_like(mask))


_broadcast_from_last.defvjp(_broadcast_from_last_fwd,
                            _broadcast_from_last_bwd)


def pipeline_apply(stage_fn: Callable, stage_params, x, *,
                   axis_name: str = "pipe", n_microbatches: int):
    """Run ``x`` through the pipeline.  Call inside ``shard_map`` with
    ``axis_name`` bound and ``stage_params`` sharded so each device holds
    its stage slice (leading dim 1, pre-squeezed by the in_spec).

    ``stage_fn(stage_params, x_mb) -> y_mb`` with matching shapes (the
    homogeneous-stage constraint standard pipelines share).
    ``x``: [B, ...] with B divisible by ``n_microbatches``.
    Returns [B, ...], identical on every pipe shard.
    """
    n_stages = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    M = n_microbatches
    B = x.shape[0]
    if B % M != 0:
        raise ValueError(f"batch {B} not divisible by {M} microbatches")
    mb = B // M
    micro = x.reshape((M, mb) + x.shape[1:])

    # Activations hop stage i -> i+1; the wrap edge only carries bubble
    # garbage, and a ring permute keeps the collective uniform.
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    state = jnp.zeros((mb,) + x.shape[1:], x.dtype)
    outputs = jnp.zeros((M, mb) + x.shape[1:], x.dtype)

    for t in range(M + n_stages - 1):
        feed = micro[min(t, M - 1)]
        inp = jnp.where(idx == 0, feed, state)
        out = stage_fn(stage_params, inp)
        j = t - (n_stages - 1)
        if 0 <= j < M:
            keep = jnp.where(idx == n_stages - 1, out, outputs[j])
            outputs = outputs.at[j].set(keep)
        state = lax.ppermute(out, axis_name, perm)

    # Everyone receives the final result (masked psum = broadcast from the
    # last stage) so loss/metrics can be computed replicated.
    mask = (idx == n_stages - 1).astype(outputs.dtype)
    outputs = _broadcast_from_last(outputs, mask, axis_name)
    return outputs.reshape((B,) + x.shape[1:])


# ---------------------------------------------------------------------------
# Pipelined Llama (the framework's PP training path)
# ---------------------------------------------------------------------------

def init_pipelined_llama(cfg, rng, n_stages: int):
    """Init Llama params in pipeline layout.

    Returns ``{"stages": <stacked layer params [n_stages, L/n_stages, ...]>,
    "rest": {tok_emb, norm_f, lm_head}}``.  Place ``stages`` leaves with
    ``NamedSharding(mesh, P("pipe"))`` so each device materializes one
    stage.
    """
    from horovod_tpu.models.llama import LlamaModel

    if cfg.num_layers % n_stages != 0:
        raise ValueError(
            f"{cfg.num_layers} layers not divisible into {n_stages} stages")
    model = LlamaModel(cfg)
    ids = jnp.zeros((1, 8), jnp.int32)
    params = model.init(rng, ids)["params"]
    layers = [params[f"layer_{i}"] for i in range(cfg.num_layers)]
    staged = jax.tree.map(
        lambda a: a.reshape(
            (n_stages, cfg.num_layers // n_stages) + a.shape[1:]),
        stack_pytrees(layers))
    rest = {"tok_emb": params["tok_emb"], "norm_f": params["norm_f"],
            "lm_head": params["lm_head"]}
    return {"stages": staged, "rest": rest}


def make_pipelined_llama_train_step(cfg, optimizer, mesh, *,
                                    n_microbatches: int,
                                    pipe_axis: str = "pipe",
                                    donate: bool = True):
    """Jitted LM train step with the layer stack pipelined over
    ``pipe_axis`` and batch sharded over the data-like axes.

    Hybrid design: loss+grads run in ``shard_map`` (explicit microbatch
    ppermute pipeline, data-axis psum of gradients); the optimizer update
    runs at the GSPMD level so optimizer state inherits each parameter's
    sharding (stage-sharded for stage params) with no manual spec plumbing.

    ``step(params, opt_state, inputs, targets) ->
    (params, opt_state, loss)`` with ``params`` from
    :func:`init_pipelined_llama`.

    FSDP composition: wrapping the optimizer as
    ``DistributedOptimizer(inner, fsdp=True)`` on a mesh with a
    non-trivial ``fsdp`` axis shards the GSPMD-level OPTIMIZER STATE
    over that axis (each moment tensor constrained to 1/|fsdp| per
    device; XLA inserts the allgather/reducescatter halves around the
    update).  The batch already shards over the data-LIKE axes —
    ``data`` and ``fsdp`` both carry microbatches — so pipeline × fsdp
    × data coexist on one mesh: ``build_mesh({"pipe": P, "fsdp": F,
    "data": D})``.  This is the in-jit rung of the sharding ladder; the
    host-driven eager rung is ``runtime/fsdp.py`` (docs/zero.md).
    """
    import optax
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from horovod_tpu.models.llama import LlamaLayer, rope_freqs
    from horovod_tpu.parallel.mesh import AXIS_FSDP, data_axes

    from horovod_tpu.jax import DistributedOptimizer

    fsdp_axis = None
    if isinstance(optimizer, DistributedOptimizer):
        if getattr(optimizer, "_fsdp", False) \
                and AXIS_FSDP in mesh.axis_names \
                and mesh.shape[AXIS_FSDP] > 1:
            fsdp_axis = AXIS_FSDP
        # Gradients are already data-psum'd inside the shard_map below.
        optimizer = optimizer.inner

    batch_axes = tuple(data_axes(mesh)) or ()
    layer_mod = LlamaLayer(cfg)

    def stage_fn(stage_params, x):
        cos, sin = rope_freqs(cfg.head_dim, x.shape[1], cfg.rope_theta)

        def body(h, lp):
            return layer_mod.apply({"params": lp}, h, cos, sin), None

        out, _ = lax.scan(body, x, stage_params)
        return out

    def _local_loss(stages, rest, inputs, targets):
        emb = jnp.take(rest["tok_emb"]["embedding"], inputs,
                       axis=0).astype(cfg.dtype)
        h = pipeline_apply(stage_fn, stages, emb, axis_name=pipe_axis,
                           n_microbatches=n_microbatches)
        h32 = h.astype(jnp.float32)
        h32 = h32 * lax.rsqrt(
            jnp.mean(h32 * h32, axis=-1, keepdims=True) + cfg.rms_eps)
        h = (h32 * rest["norm_f"]["scale"]).astype(cfg.dtype)
        logits = (h @ rest["lm_head"]["kernel"]).astype(jnp.float32)
        # Local sum in lse form (no fp32 log-prob tensor).
        return softmax_cross_entropy(logits, targets, reduction="sum")

    def _grads(stages_sharded, rest, inputs, targets):
        stages = jax.tree.map(lambda a: a[0], stages_sharded)
        n_data = lax.axis_size(batch_axes) if batch_axes else 1
        denom = inputs.shape[0] * n_data * inputs.shape[1]
        loss_sum, grads = jax.value_and_grad(
            _local_loss, argnums=(0, 1))(stages, rest, inputs, targets)
        g_stages, g_rest = grads
        # Horovod pattern (check_vma=False + explicit grad psums — same
        # discipline as make_train_step and the seq builder, and identical
        # on both jax AD lines, where VMA-aware AD would instead insert
        # these reductions itself): each shard holds partial cotangents.
        # tok_emb feeds the pipeline INPUT, so its cotangent lives only on
        # the stage-0 shard — collect it with a psum over pipe.  norm_f /
        # lm_head act on the replicated broadcast OUTPUT, so every pipe
        # shard already holds their full cotangent — no pipe reduction.
        # Everything then reduces over the data axes it is invariant to.
        g_rest = dict(g_rest)
        g_rest["tok_emb"] = jax.tree.map(
            lambda a: lax.psum(a, pipe_axis), g_rest["tok_emb"])
        if batch_axes:
            loss_sum = lax.psum(loss_sum, batch_axes)
            g_stages = jax.tree.map(lambda a: lax.psum(a, batch_axes),
                                    g_stages)
            g_rest = jax.tree.map(lambda a: lax.psum(a, batch_axes),
                                  g_rest)
        g_stages = jax.tree.map(lambda a: a[None] / denom, g_stages)
        g_rest = jax.tree.map(lambda a: a / denom, g_rest)
        return loss_sum / denom, {"stages": g_stages, "rest": g_rest}

    stage_specs = P(pipe_axis)
    batch_spec = P(tuple(batch_axes) if batch_axes else None)

    def _fsdp_state_spec(shape, n_stages):
        """ZeRO spec for one optimizer-state leaf: stage-stacked moments
        keep their pipe dim, then the first remaining dim divisible by
        the fsdp axis shards over it (scalars and indivisible leaves
        stay replicated — counts, tiny norms)."""
        fsdp_size = mesh.shape[fsdp_axis]
        spec = [None] * len(shape)
        start = 0
        if shape and shape[0] == n_stages:
            spec[0] = pipe_axis
            start = 1
        for d in range(start, len(shape)):
            if shape[d] >= fsdp_size and shape[d] % fsdp_size == 0:
                spec[d] = fsdp_axis
                break
        return P(*spec)

    def _constrain_opt_state(opt_state, n_stages):
        def leaf(a):
            if not hasattr(a, "shape"):
                return a
            return lax.with_sharding_constraint(
                a, NamedSharding(mesh,
                                 _fsdp_state_spec(a.shape, n_stages)))
        return jax.tree.map(leaf, opt_state)

    def step(params, opt_state, inputs, targets):
        loss, grads = shard_map(
            _grads, mesh=mesh,
            in_specs=(
                jax.tree.map(lambda _: stage_specs, params["stages"]),
                jax.tree.map(lambda _: P(), params["rest"]),
                batch_spec, batch_spec),
            out_specs=(
                P(),
                {"stages": jax.tree.map(lambda _: stage_specs,
                                        params["stages"]),
                 "rest": jax.tree.map(lambda _: P(), params["rest"])}),
            check_vma=False,
        )(params["stages"], params["rest"], inputs, targets)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        if fsdp_axis is not None:
            n_stages = jax.tree.leaves(params["stages"])[0].shape[0]
            opt_state = _constrain_opt_state(opt_state, n_stages)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    donate_args = (0, 1) if donate else ()
    return jax.jit(step, donate_argnums=donate_args)
