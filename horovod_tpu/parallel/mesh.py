"""Device-mesh construction and global default-mesh management.

Reference parity: the MPI communicator setup in ``BackgroundThreadLoop``
(``horovod/common/operations.cc:1469-1532``) — world comm, the
``MPI_Comm_split_type(MPI_COMM_TYPE_SHARED)`` *local* comm and the
``MPI_Comm_split(local_rank)`` *cross* comm that power hierarchical
allreduce (operations.cc:1025-1187).

TPU-native design: the communicator hierarchy becomes a ``jax.sharding.Mesh``.
The local/cross split maps onto ICI-within-slice vs DCN-across-slices: when
multiple processes (hosts/slices) are present we build a *hybrid* device mesh
(``mesh_utils.create_hybrid_device_mesh``) so that the innermost mesh axes
ride ICI and only the outermost crosses DCN — the exact analogue of
NCCL-reduce-scatter → cross-node-MPI-allreduce → NCCL-all-gather, except XLA
inserts the decomposition for us.

Named axes follow the scaling-book convention:
  ``data``    — pure data parallelism (gradient psum)
  ``fsdp``    — data parallelism with sharded params/optimizer state
  ``tensor``  — tensor/model parallelism (activations sharded)
  ``seq``     — sequence/context parallelism (ring attention / all-to-all)
  ``expert``  — expert parallelism for MoE layers
"""

from __future__ import annotations

import contextlib
import math
import threading
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

__all__ = [
    "AXIS_DATA",
    "AXIS_FSDP",
    "AXIS_TENSOR",
    "AXIS_SEQ",
    "AXIS_EXPERT",
    "build_mesh",
    "data_parallel_mesh",
    "default_mesh",
    "set_default_mesh",
    "use_mesh",
    "mesh_axis_size",
    "data_axes",
]

AXIS_DATA = "data"
AXIS_FSDP = "fsdp"
AXIS_TENSOR = "tensor"
AXIS_SEQ = "seq"
AXIS_EXPERT = "expert"

# Axes over which gradients are reduced (batch-like axes).
_DATA_LIKE_AXES = (AXIS_DATA, AXIS_FSDP)

_state = threading.local()


def _resolve_shape(axes: dict[str, int], n_devices: int) -> dict[str, int]:
    """Fill in a single -1 wildcard so the product equals n_devices."""
    shape = dict(axes)
    wild = [k for k, v in shape.items() if v == -1]
    if len(wild) > 1:
        raise ValueError("at most one mesh axis may be -1")
    fixed = math.prod(v for v in shape.values() if v != -1)
    if wild:
        if n_devices % fixed != 0:
            raise ValueError(
                f"cannot infer axis {wild[0]!r}: {n_devices} devices not "
                f"divisible by {fixed}"
            )
        shape[wild[0]] = n_devices // fixed
    elif fixed != n_devices:
        raise ValueError(
            f"mesh shape {shape} does not cover {n_devices} devices"
        )
    return shape


def build_mesh(
    axes: Optional[dict[str, int]] = None,
    *,
    devices: Optional[Sequence[jax.Device]] = None,
    allow_split_physical_axes: bool = False,
) -> Mesh:
    """Build a Mesh with named axes over all (or given) devices.

    ``axes`` maps axis name -> size, with at most one ``-1`` wildcard, e.g.
    ``{"data": -1}`` or ``{"data": -1, "tensor": 4}``.  Axis order is
    significant: later axes are innermost (most-contiguous on ICI), so put
    the most communication-hungry axis (tensor/seq) last.

    Multi-process topologies get a hybrid mesh whose outermost axis spans
    processes (DCN) — the TPU-native "cross communicator".
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if axes is None:
        axes = {AXIS_DATA: n}
    shape = _resolve_shape(axes, n)
    names = tuple(shape.keys())
    sizes = tuple(shape[k] for k in names)

    n_proc = getattr(jax, "process_count", lambda: 1)()
    mesh_devices = None
    if n_proc > 1 and n % n_proc == 0:
        try:
            from jax.experimental import mesh_utils

            per_proc = n // n_proc
            # Split each mesh axis into a DCN (across-process) component and
            # an ICI component, outermost-first, mirroring cross/local comms.
            dcn_left = n_proc
            dcn_shape, ici_shape = [], []
            for s in sizes:
                g = math.gcd(s, dcn_left)
                dcn_shape.append(g)
                ici_shape.append(s // g)
                dcn_left //= g
            if dcn_left == 1 and math.prod(ici_shape) == per_proc:
                mesh_devices = mesh_utils.create_hybrid_device_mesh(
                    ici_shape, dcn_shape, devices=devices,
                    allow_split_physical_axes=allow_split_physical_axes,
                )
        except Exception:
            mesh_devices = None
    if mesh_devices is None:
        try:
            from jax.experimental import mesh_utils

            mesh_devices = mesh_utils.create_device_mesh(
                sizes, devices=np.asarray(devices),
                allow_split_physical_axes=allow_split_physical_axes,
            )
        except Exception:
            mesh_devices = np.asarray(devices).reshape(sizes)
    return Mesh(mesh_devices, names)


def data_parallel_mesh(devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """The default Horovod-like topology: every chip on one ``data`` axis."""
    return build_mesh({AXIS_DATA: -1}, devices=devices)


def default_mesh() -> Mesh:
    """Return the active mesh, building a data-parallel one on first use."""
    mesh = getattr(_state, "mesh", None)
    if mesh is None:
        mesh = data_parallel_mesh()
        _state.mesh = mesh
    return mesh


def set_default_mesh(mesh: Optional[Mesh]) -> None:
    _state.mesh = mesh


@contextlib.contextmanager
def use_mesh(mesh: Mesh):
    """Context manager installing ``mesh`` as the default."""
    prev = getattr(_state, "mesh", None)
    _state.mesh = mesh
    try:
        yield mesh
    finally:
        _state.mesh = prev


def mesh_axis_size(axis_name, mesh: Optional[Mesh] = None) -> int:
    mesh = mesh or default_mesh()
    if isinstance(axis_name, (tuple, list)):
        return math.prod(mesh.shape[a] for a in axis_name)
    return mesh.shape[axis_name]


def data_axes(mesh: Optional[Mesh] = None) -> tuple[str, ...]:
    """The batch-like axes of ``mesh`` (gradient-reduction axes)."""
    mesh = mesh or default_mesh()
    return tuple(a for a in mesh.axis_names if a in _DATA_LIKE_AXES)
