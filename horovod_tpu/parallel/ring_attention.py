"""Ring attention: sequence/context parallelism over ICI neighbors.

No reference equivalent — Horovod 0.15.1 has no attention or sequence
machinery (SURVEY.md §5.7) — but long-context support is first-class in
this framework.  Design follows the blockwise ring-attention construction
(Liu et al.; "How to Scale Your Model" ch. on context parallelism):

* the sequence axis is sharded over a mesh axis (``seq``);
* each device holds one query block Q_i and starts with its KV block;
* KV blocks rotate around the ring via ``lax.ppermute`` (nearest-neighbor
  ICI transfers that overlap with each block's attention compute);
* softmax is accumulated *online* (running max + normalizer), so the full
  [S, S] score matrix never materializes — memory is O(S_local²) per step;
* causal masking is block-aware: with Q block index i and KV block j,
  j > i contributes nothing (skipped numerically via full masking), j == i
  applies the intra-block triangle, j < i is unmasked.

Gradients flow through ppermute (its transpose is the reverse rotation),
so ``jax.grad`` of a ring-attention loss is itself a ring computation —
no custom VJP needed for correctness.  Use inside ``shard_map`` with the
``seq`` axis bound; wrap with ``make_ring_attention_fn`` to drop into the
model zoo's pluggable ``attention_fn`` seam.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

import horovod_tpu.common.jax_compat  # noqa: F401  (lax.axis_size shim)

__all__ = ["ring_attention", "make_ring_attention_fn", "ulysses_attention"]

_NEG_INF = jnp.finfo(jnp.float32).min


def _block_attend(q, k, v, mask):
    """Scores and weighted values for one (Q block, KV block) pair.

    q: [B, Sq, H, D]; k, v: [B, Sk, Hkv, D] (GQA-aware).
    Returns (scores [B, H, Sq, Sk] fp32, values path deferred to caller).
    """
    B, Sq, Hq, D = q.shape
    Hkv = k.shape[2]
    group = Hq // Hkv
    qg = q.reshape(B, Sq, Hkv, group, D)
    scores = jnp.einsum("bqhgd,bkhd->bhgqk", qg, k).astype(jnp.float32)
    scores = scores / jnp.sqrt(jnp.asarray(D, jnp.float32))
    if mask is not None:
        scores = jnp.where(mask, scores, _NEG_INF)
    return scores


def _ring_attention_flash(q, k, v, axis_name, causal):
    """Ring attention with the Pallas flash kernel as the per-hop block
    attention: each hop computes ``(out_t, lse_t)`` via
    ``flash_attention_lse`` and the hops merge by log-sum-exp weights —
    so no [B, H, S_loc, S_loc] fp32 score block ever materializes, per
    hop memory is O(S_loc * D), and AD flows through both kernel outputs
    (the lse cotangent rides the backward kernels' delta sideband).

    Hop visibility under causality is BLOCK-level: hop t carries the KV
    block of shard ``src = (my - t) mod n``; t == 0 is the causal
    diagonal (static flag), src < my is fully visible, src > my is
    killed by setting its lse to -inf (weight 0 in the merge — the
    compute still runs, matching the XLA path's lockstep cost).
    """
    from horovod_tpu.ops.flash_attention import flash_attention_lse

    axis_size = lax.axis_size(axis_name)
    my_idx = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
    # Online pairwise merge: O(1)-hop accumulators, like the XLA path
    # below — never a [T, ...] stack of hop outputs.
    out_acc = lse_acc = None                        # f32 [B,Sq,H,D]/[B,H,Sq]
    k_blk, v_blk = k, v
    for t in range(axis_size):
        o_t, lse_t = flash_attention_lse(q, k_blk, v_blk,
                                         causal=(causal and t == 0))
        o_t = o_t.astype(jnp.float32)
        if causal and t > 0:
            src = (my_idx - t) % axis_size
            lse_t = jnp.where(src < my_idx, lse_t, -jnp.inf)
        if t == 0:
            # The t=0 hop (the causal diagonal) is never masked, so the
            # accumulators start finite.
            out_acc, lse_acc = o_t, lse_t
        else:
            new_lse = jnp.logaddexp(lse_acc, lse_t)  # -inf hops: no-op
            w_old = jnp.exp(lse_acc - new_lse)
            w_new = jnp.exp(lse_t - new_lse)
            out_acc = (out_acc * jnp.moveaxis(w_old, 1, 2)[..., None]
                       + o_t * jnp.moveaxis(w_new, 1, 2)[..., None])
            lse_acc = new_lse
        if t < axis_size - 1:
            k_blk = lax.ppermute(k_blk, axis_name, perm)
            v_blk = lax.ppermute(v_blk, axis_name, perm)
    return out_acc.astype(q.dtype)


def ring_attention(q, k, v, *, axis_name: str = "seq", causal: bool = True):
    """Blockwise attention with KV rotating around the ``axis_name`` ring.

    Shapes (per shard): q [B, S_loc, H, D]; k, v [B, S_loc, Hkv, D] with
    H % Hkv == 0 (GQA).  Sequence order is the natural shard order: shard
    ``i`` holds positions [i*S_loc, (i+1)*S_loc).  Returns [B, S_loc, H, D].

    When the local shard fits the flash kernel (S_loc a block multiple;
    off-tile head dims are padded inside the kernel wrapper), each hop's
    block attention runs the Pallas kernel and hops merge by log-sum-exp
    (see :func:`_ring_attention_flash`); otherwise the XLA
    online-softmax path below runs.
    """
    from horovod_tpu.ops.flash_attention import (_note_fallback,
                                                 flash_lse_supported)

    if flash_lse_supported(q.shape[1], q.shape[3]) \
            and k.shape[1] == q.shape[1]:
        return _ring_attention_flash(q, k, v, axis_name, causal)
    # The lse-returning kernel owns no sequence-padding shim; count the
    # XLA-path choice so losing the per-hop kernel is visible
    # (ops.flash_attention.fallback_count telemetry) whichever condition
    # failed.
    if not flash_lse_supported(q.shape[1], q.shape[3]):
        _note_fallback(
            f"ring attention hop uses the XLA online-softmax path: "
            f"local shard length {q.shape[1]} is off the lse-kernel "
            f"tiling (needs a multiple of 128)")
    else:
        _note_fallback(
            f"ring attention hop uses the XLA online-softmax path: KV "
            f"shard length {k.shape[1]} != Q shard length {q.shape[1]}")

    axis_size = lax.axis_size(axis_name)
    my_idx = lax.axis_index(axis_name)
    B, Sq, Hq, D = q.shape
    Hkv = k.shape[2]
    group = Hq // Hkv

    # Online-softmax accumulators (fp32).
    o = jnp.zeros((B, Hkv, group, Sq, D), jnp.float32)
    m = jnp.full((B, Hkv, group, Sq), _NEG_INF, jnp.float32)
    l = jnp.zeros((B, Hkv, group, Sq), jnp.float32)

    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
    q_pos = jnp.arange(Sq)
    k_pos = jnp.arange(k.shape[1])

    def step(carry, t):
        o, m, l, k_blk, v_blk = carry
        # KV block t hops ago originated at shard (my_idx - t) mod size.
        src = (my_idx - t) % axis_size
        scores = _block_attend(q, k_blk, v_blk, None)  # [B,Hkv,g,Sq,Sk]
        if causal:
            # Global positions: q at my_idx*Sq + q_pos, k at src*Sk + k_pos.
            qg = my_idx * Sq + q_pos
            kg = src * k.shape[1] + k_pos
            mask = qg[:, None] >= kg[None, :]
            scores = jnp.where(mask[None, None, None], scores, _NEG_INF)
        blk_max = jnp.max(scores, axis=-1)                     # [B,Hkv,g,Sq]
        new_m = jnp.maximum(m, blk_max)
        # Guard fully-masked rows (new_m == -inf): exp(0)=1 would poison l;
        # alpha/beta formulation keeps them at zero contribution.
        safe_m = jnp.where(jnp.isfinite(new_m), new_m, 0.0)
        alpha = jnp.where(jnp.isfinite(m), jnp.exp(m - safe_m), 0.0)
        p = jnp.exp(scores - safe_m[..., None])
        p = jnp.where(jnp.isfinite(scores), p, 0.0)
        new_l = l * alpha + jnp.sum(p, axis=-1)
        pv = jnp.einsum("bhgqk,bkhd->bhgqd", p.astype(v_blk.dtype),
                        v_blk).astype(jnp.float32)
        new_o = o * alpha[..., None] + pv
        # Rotate KV to the next shard (overlaps with next block's compute).
        k_next = lax.ppermute(k_blk, axis_name, perm)
        v_next = lax.ppermute(v_blk, axis_name, perm)
        return (new_o, new_m, new_l, k_next, v_next), None

    carry = (o, m, l, k, v)
    for t in range(axis_size):
        carry, _ = step(carry, t)
    o, m, l, _, _ = carry
    out = o / jnp.maximum(l[..., None], 1e-30)
    # [B,Hkv,g,Sq,D] -> [B,Sq,H,D]
    out = jnp.moveaxis(out, 3, 1).reshape(B, Sq, Hq, D)
    return out.astype(q.dtype)


def make_ring_attention_fn(axis_name: str = "seq"):
    """Adapter matching the model zoo's ``attention_fn(q, k, v)`` seam
    (horovod_tpu.models.llama.causal_attention signature)."""

    def attention_fn(q, k, v, *args, **kwargs):
        return ring_attention(q, k, v, axis_name=axis_name, causal=True)

    return attention_fn


def ulysses_attention(q, k, v, *, axis_name: str = "seq",
                      causal: bool = True):
    """DeepSpeed-Ulysses-style sequence parallelism: all-to-all from
    sequence-sharded to head-sharded, full-sequence attention locally,
    all-to-all back.  One big ICI transfer instead of ring hops — better
    when heads >= ring size and sequence blocks are small.

    Per-shard shapes as in :func:`ring_attention`; requires H (and Hkv)
    divisible by the axis size.
    """
    axis_size = lax.axis_size(axis_name)
    B, Sq, Hq, D = q.shape
    if Hq % axis_size != 0 or k.shape[2] % axis_size != 0:
        raise ValueError(
            f"ulysses requires heads ({Hq}, kv {k.shape[2]}) divisible by "
            f"the {axis_name!r} axis size {axis_size}")
    # [B, S_loc, H, D] -> [B, S_full, H/P, D]
    qh = lax.all_to_all(q, axis_name, split_axis=2, concat_axis=1, tiled=True)
    kh = lax.all_to_all(k, axis_name, split_axis=2, concat_axis=1, tiled=True)
    vh = lax.all_to_all(v, axis_name, split_axis=2, concat_axis=1, tiled=True)
    # The local attention runs over the FULL sequence — exactly where the
    # Pallas flash kernel earns its keep (the dense path materializes
    # [B, H, S, S] scores).  shard_map bodies are Manual-mesh, so the
    # kernel lowers legally here; off-tile head dims are zero-padded to
    # the kernel inside flash_attention (no dense path).
    from horovod_tpu.ops.flash_attention import flash_attention

    out = flash_attention(qh, kh, vh, causal=causal)
    # [B, S_full, H/P, D] -> [B, S_loc, H, D]
    return lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2,
                          tiled=True)
