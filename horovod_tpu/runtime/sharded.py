"""ZeRO-style sharded optimizer core (Rajbhandari et al., "ZeRO: Memory
Optimizations Toward Training Trillion Parameter Models" — stage 1/2).

The idea: with N data-parallel ranks, keeping N identical copies of the
optimizer state (and fp32 master weights) wastes (N-1)/N of that memory.
Instead, flatten every parameter into ONE flat vector, split it with the
engine's largest-first dim-0 convention (the same split the coordinator
commits for ``reducescatter``), and let each rank keep optimizer state
ONLY for its owned shard.  A step becomes

    reducescatter(flat grads)          # this rank's shard of the SUM
    local update of the owned shard    # elementwise optimizer math
    allgather(shard updates/params)    # everyone leaves with full params

Bit-exactness contract: because the flat vector is 1-D, the committed
shard geometry coincides with the ring's own segments, so
``reducescatter(g)[rank]`` is BIT-FOR-BIT ``allreduce(g)`` sliced to the
owned shard (asserted per dtype in tests/test_reducescatter.py).  An
ELEMENTWISE optimizer (SGD, momentum, Adam, AdamW, ...) then computes on
the shard exactly the bytes it would have computed on the full vector,
and the allgather moves bytes verbatim — so a ``sharded=True`` step is
bit-identical to the equivalent unsharded flat step.  Optimizers with
CROSS-parameter reductions (global grad-norm clipping) break that
equivalence; compose them outside the sharded wrapper or accept the
shard-local norm.

Wire accounting (honest — ZeRO's own Table 1 says the same): the
gradient reduce-scatter moves HALF the bytes of an allreduce, and the
parameter allgather moves the other half, so a sharded step's total wire
bytes match the unsharded step while per-rank optimizer-state memory
drops to ~1/N.  The gradient-path ratio (~0.5, gated at <= 0.55 in ci)
is what composes with wire compression; the memory is the lever that
lets a model grow past per-rank RAM.

Resize semantics: the shard split is a pure function of (flat length,
world size), anchored at construction with the membership epoch.  An
elastic resize that keeps the world size re-anchors silently (the shard
layout is unchanged).  A resize that CHANGES the world size raises
:class:`ShardResizeError` — the optimizer state lives only on its owner,
so silently continuing would corrupt the run; rebuild the optimizer (and
re-broadcast params) from the last checkpoint or committed state instead
(see docs/zero.md).

Deliberately jax/torch-free (numpy + the native engine), like
runtime.engine — both frontends drive this core.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Tuple

import numpy as np

from horovod_tpu.runtime import engine_or_none
from horovod_tpu.runtime.engine import note_sharded_step

__all__ = ["shard_bounds", "my_shard", "ShardResizeError", "FlatSharder",
           "sharded_default"]


class ShardResizeError(RuntimeError):
    """The world size changed under a sharded optimizer: the shard
    layout (and with it the ownership of optimizer state) is no longer
    the one this state was built for.  Rebuild the optimizer from a
    checkpoint / committed ElasticState for the new world — continuing
    would silently corrupt the update."""


def sharded_default() -> bool:
    """The ``HOROVOD_SHARDED`` env default for
    ``DistributedOptimizer(sharded=None)`` (0/off unless set)."""
    import os

    raw = os.environ.get("HOROVOD_SHARDED", "")
    return raw.strip() not in ("", "0", "false", "False")


def shard_bounds(n: int, size: int) -> List[Tuple[int, int]]:
    """Per-rank ``(offset, count)`` of the flat length-``n`` vector under
    the engine's committed largest-first split: ``n // size`` each, the
    first ``n % size`` ranks take one extra.  MUST stay in lockstep with
    the coordinator's reducescatter geometry (cpp/engine.cc
    BuildResponse) — for a 1-D tensor the two are the same formula,
    which is exactly what makes the RS half bit-exact."""
    bounds = []
    off = 0
    for r in range(size):
        cnt = n // size + (1 if r < n % size else 0)
        bounds.append((off, cnt))
        off += cnt
    return bounds


def my_shard(n: int, rank: int, size: int) -> Tuple[int, int]:
    """This rank's ``(offset, count)`` of the flat vector."""
    return shard_bounds(n, size)[rank]


class FlatSharder:
    """Flat-vector partitioner + the RS/AG step plumbing both frontends
    share.

    Owns: the world anchor (epoch, size, flat length, shard bounds) and
    the wire ops.  Does NOT own optimizer math — the caller passes a
    ``local_update(shard_grads) -> shard_updates`` callback (jax: optax
    on the shard; torch: the shard optimizer's step), keeping this core
    dependency-free.

    ``name`` namespaces the collective names (``<name>.rs.grads``,
    ``sharded.ag.<name>``); instantiate one sharder per optimizer.
    """

    #: Per-process construction counter: two sharded optimizers in one
    #: process get distinct collective names, and the names still agree
    #: across ranks because construction follows program order — the
    #: same contract as the engine's auto-naming.
    _instances = 0

    def __init__(self, n: int, dtype, *, name: str = "zero"):
        self.n = int(n)
        self.dtype = np.dtype(dtype)
        self.name = f"{name}.{FlatSharder._instances}"
        FlatSharder._instances += 1
        eng = engine_or_none()
        from horovod_tpu.common.basics import basics

        self.size = basics.size() if basics.is_initialized() else 1
        self.rank = basics.rank() if basics.is_initialized() else 0
        self.epoch = eng.epoch() if eng is not None else 0
        self.offset, self.count = my_shard(self.n, self.rank, self.size)
        self._steps = 0

    # -- anchors --

    def check_world(self) -> None:
        """Re-anchor on a same-size epoch bump (shard layout unchanged);
        raise :class:`ShardResizeError` when the world size moved."""
        eng = engine_or_none()
        from horovod_tpu.common.basics import basics

        size = basics.size() if basics.is_initialized() else 1
        epoch = eng.epoch() if eng is not None else 0
        if size != self.size:
            raise ShardResizeError(
                f"sharded optimizer '{self.name}' was built for world "
                f"size {self.size} (epoch {self.epoch}) but the committed "
                f"world is now size {size} (epoch {epoch}); the shard "
                "layout changed, so per-rank optimizer state no longer "
                "matches its owner. Rebuild the optimizer from a "
                "checkpoint/ElasticState for the new world (docs/zero.md)."
            )
        self.epoch = epoch

    # -- the step halves --

    def reduce_grads(self, flat_grads: np.ndarray, *, average: bool = True,
                     wire_dtype: Optional[str] = None) -> np.ndarray:
        """This rank's shard of the gradient reduction: ONE engine
        reducescatter of the flat vector (half an allreduce's wire
        bytes), divisor-correct by the committed participant count.
        Returns the shard (length ``self.count``)."""
        self.check_world()
        flat = np.ascontiguousarray(flat_grads, dtype=self.dtype)
        if flat.size != self.n:
            raise ValueError(
                f"flat gradient length {flat.size} != sharder length "
                f"{self.n}")
        eng = engine_or_none()
        if eng is None:
            shard = flat[self.offset:self.offset + self.count].copy()
            return shard
        # Stable name: the response cache negotiates steady-state steps
        # via a slot bit (a per-step suffix would miss every cycle).
        info: dict = {}
        out = eng.synchronize(
            eng.enqueue_reducescatter(
                flat, name=f"{self.name}.rs.grads",
                wire_dtype=wire_dtype),
            info)
        if average:
            out = eng._apply_average(out,
                                     info.get("participants") or None)
        return out

    def gather_updates(self, shard_updates: np.ndarray) -> np.ndarray:
        """The inverse half: allgather every rank's shard back into the
        full flat vector (named ``sharded.ag.*`` so the engine's
        AG_PARAMS timeline span attributes it)."""
        upd = np.ascontiguousarray(shard_updates)
        if upd.size != self.count:
            raise ValueError(
                f"shard update length {upd.size} != owned shard "
                f"{self.count}")
        eng = engine_or_none()
        if eng is None:
            return upd
        out = eng.allgather(upd, name=f"sharded.ag.{self.name}")
        return np.asarray(out)

    def step(self, flat_grads: np.ndarray,
             local_update: Callable[[np.ndarray], np.ndarray], *,
             average: bool = True,
             wire_dtype: Optional[str] = None) -> np.ndarray:
        """One full ZeRO step over the flat vector: RS → ``local_update``
        on the owned shard → AG.  Returns the FULL flat update vector
        (what the frontends unflatten back into the param pytree)."""
        shard_g = self.reduce_grads(flat_grads, average=average,
                                    wire_dtype=wire_dtype)
        shard_u = local_update(shard_g)
        full = self.gather_updates(np.asarray(shard_u, dtype=self.dtype))
        self._steps += 1
        note_sharded_step()
        return full

    # -- flat <-> pytree-of-arrays helpers (numpy level; the frontends
    #    handle their own tree flattening and just hand lists here) --

    @staticmethod
    def flatten(arrays: List[np.ndarray], dtype) -> np.ndarray:
        """Concatenate arrays (C-order raveled) into one flat vector."""
        if not arrays:
            return np.zeros((0,), dtype=dtype)
        return np.concatenate(
            [np.ascontiguousarray(a, dtype=dtype).ravel() for a in arrays])

    @staticmethod
    def slice_flat(arrays: List[np.ndarray], offset: int, count: int,
                   dtype) -> np.ndarray:
        """The ``[offset, offset+count)`` window of the VIRTUAL
        concatenation of ``arrays`` without materializing it: only
        leaves overlapping the window are raveled/converted.  This is
        how the frontends fetch the shard of the PARAMS each step — a
        full flat copy of the model would reintroduce the O(N) host
        buffer the 1/N-memory design exists to avoid (gradients are
        different: the reduce-scatter wire genuinely needs the full
        flat vector once per step)."""
        parts, pos = [], 0
        end = offset + count
        for a in arrays:
            arr = np.asarray(a)
            n = int(arr.size)
            lo, hi = max(offset, pos), min(end, pos + n)
            if lo < hi:
                flat = np.ascontiguousarray(arr, dtype=dtype).ravel()
                parts.append(flat[lo - pos:hi - pos])
            pos += n
        if not parts:
            return np.zeros(0, dtype=dtype)
        if len(parts) == 1:
            return np.ascontiguousarray(parts[0], dtype=dtype)
        return np.concatenate(parts)

    @staticmethod
    def unflatten(flat: np.ndarray, shapes: List[tuple]) -> List[np.ndarray]:
        """Split the flat vector back into arrays of ``shapes``."""
        outs, off = [], 0
        for shp in shapes:
            cnt = int(np.prod(shp)) if shp else 1
            outs.append(flat[off:off + cnt].reshape(shp))
            off += cnt
        return outs
