"""ZeRO-3/FSDP parameter-sharding plane (Rajbhandari et al. stage 3;
Zhao et al., "PyTorch FSDP" — the production pattern).

ZeRO-1 (`runtime/sharded.py`) shards OPTIMIZER STATE: every rank still
holds full params and full grads, so model size caps at per-rank RAM.
This plane shards the PARAMETERS themselves.  The model is cut into
per-layer **units**; each unit's params are flattened onto a
:class:`~horovod_tpu.runtime.sharded.FlatSharder` window (the same
largest-first split and epoch-stamped world anchor ZeRO-1 uses — which
is what keeps every collective bit-exact against the unsharded anchor),
and each rank retains only its owned window:

* **forward**: :meth:`FsdpPlane.gather` allgathers a unit's shards
  just-in-time and enqueues the NEXT unit's allgather at priority band 0
  (``HOROVOD_FSDP_PREFETCH`` units ahead, default 1) so the banded
  scheduler (HOROVOD_PRIORITY_BANDS) overlaps the wire with the current
  unit's compute;
* **backward**: :meth:`FsdpPlane.reduce_grads` reducescatters a unit's
  grads the moment its vjp completes (async handle; the PR 12 RS
  cascade), with the PR 15 advisory wire-dtype seam available per unit;
* **after use**: :meth:`FsdpPlane.free` drops the gathered full params
  immediately — peak residency is ~(owned shards + one or two gathered
  units), the 1/N memory the ci fsdp gate measures.

Bit-exactness rides the ZeRO-1 chain unchanged: 1-D flat units make
``reducescatter(g)[rank]`` bit-for-bit ``allreduce(g)`` sliced, an
elementwise shard update computes the same bytes as the full update, and
the allgather is lossless — so an FSDP step is bit-identical to the
unsharded flat step (asserted after EVERY step in tests/fsdp_worker.py).

Observability: collectives are named ``fsdp.*`` so the engine timeline
marks them ``FSDP_AG``/``FSDP_RS``; ``stats()`` gains ``fsdp_units``,
``fsdp_ag_prefetch_hits``/``_misses`` (the prefetched allgather was
complete when the unit was needed vs the gather blocked), and
``fsdp_param_bytes_resident``/``_peak`` (deterministic byte accounting
of shards + gathered fulls — the memory gate's instrument).

Deliberately jax/torch-free (numpy + the native engine), like
runtime.sharded — both frontends drive this plane.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from horovod_tpu.runtime import engine_or_none
from horovod_tpu.runtime.engine import note_sharded_step
from horovod_tpu.runtime.sharded import FlatSharder, ShardResizeError

__all__ = ["FsdpPlane", "FsdpUnit", "fsdp_default", "prefetch_default",
           "fsdp_stats", "reset_fsdp_stats", "ShardResizeError"]


def fsdp_default() -> bool:
    """The ``HOROVOD_FSDP`` env default for
    ``DistributedOptimizer(fsdp=None)`` (0/off unless set)."""
    raw = os.environ.get("HOROVOD_FSDP", "")
    return raw.strip() not in ("", "0", "false", "False")


def prefetch_default() -> int:
    """``HOROVOD_FSDP_PREFETCH`` (lenient-parsed): how many units ahead
    the forward gather enqueues at band 0.  Default 1; 0 disables
    prefetch (every gather blocks — the overlap gate's OFF arm)."""
    raw = os.environ.get("HOROVOD_FSDP_PREFETCH", "")
    try:
        return max(0, int(raw)) if raw.strip() else 1
    except ValueError:
        return 1


# -- the plane's stats() slice (Python-side, like the checkpoint
#    plane's: the registry/prefetch bookkeeping lives above the engine).
#    Gauges (units, resident, peak) carry current values in
#    stats_delta; hits/misses are cumulative counters. --

_STATS_LOCK = threading.Lock()
_UNITS = 0
_PREFETCH_HITS = 0
_PREFETCH_MISSES = 0
_RESIDENT = 0
_RESIDENT_PEAK = 0


def fsdp_stats() -> dict:
    with _STATS_LOCK:
        return {
            "fsdp_units": _UNITS,
            "fsdp_ag_prefetch_hits": _PREFETCH_HITS,
            "fsdp_ag_prefetch_misses": _PREFETCH_MISSES,
            "fsdp_param_bytes_resident": _RESIDENT,
            "fsdp_param_bytes_resident_peak": _RESIDENT_PEAK,
        }


def reset_fsdp_stats() -> None:
    """Zero the plane counters (tests; a live plane keeps its own
    bookkeeping, so only reset between plane lifetimes)."""
    global _UNITS, _PREFETCH_HITS, _PREFETCH_MISSES
    global _RESIDENT, _RESIDENT_PEAK
    with _STATS_LOCK:
        _UNITS = _PREFETCH_HITS = _PREFETCH_MISSES = 0
        _RESIDENT = _RESIDENT_PEAK = 0


def _note_units(delta: int) -> None:
    global _UNITS
    with _STATS_LOCK:
        _UNITS += delta


def _note_prefetch(hit: bool) -> None:
    global _PREFETCH_HITS, _PREFETCH_MISSES
    with _STATS_LOCK:
        if hit:
            _PREFETCH_HITS += 1
        else:
            _PREFETCH_MISSES += 1


def _note_resident(delta_bytes: int) -> None:
    global _RESIDENT, _RESIDENT_PEAK
    with _STATS_LOCK:
        _RESIDENT += int(delta_bytes)
        if _RESIDENT > _RESIDENT_PEAK:
            _RESIDENT_PEAK = _RESIDENT


class FsdpUnit:
    """One parameter unit: the shapes of its leaves, its FlatSharder
    window anchor, and this rank's owned shard (fp32, mutable — the
    update writes it in place)."""

    __slots__ = ("index", "name", "shapes", "n", "sharder", "shard")

    def __init__(self, index: int, name: str, shapes: List[tuple],
                 n: int, sharder: FlatSharder, shard: np.ndarray):
        self.index = index
        self.name = name
        self.shapes = shapes
        self.n = n
        self.sharder = sharder
        self.shard = shard


class FsdpPlane:
    """Full parameter sharding over per-layer units.

    ``unit_params`` is a sequence of units, each a list of numpy-like
    arrays (one model layer's params, say).  Construction flattens each
    unit to fp32, anchors a FlatSharder window, keeps ONLY the owned
    shard, and drops the full arrays — after ``__init__`` the plane is
    the single owner of the parameters.

    >>> plane = FsdpPlane([layer0_params, layer1_params, ...])
    >>> for i in range(plane.n_units):         # forward
    ...     w = plane.gather(i)                # JIT AG + band-0 prefetch
    ...     h = forward_layer(w, h)
    ...     plane.free(i)                      # drop non-owned params
    >>> for i in reversed(range(plane.n_units)):   # backward
    ...     w = plane.gather(i, direction=-1)
    ...     gs, h_grad = vjp_layer(w, ...)
    ...     plane.reduce_grads(i, gs)          # async RS, fires NOW
    ...     plane.free(i)
    >>> for i in range(plane.n_units):         # optimizer
    ...     g = plane.wait_grads(i)
    ...     update_shard_inplace(plane.shard(i), g)
    >>> plane.step()

    Every rank must construct the plane with the same unit boundaries
    (collective names follow program order, like the engine's
    auto-naming).
    """

    #: Per-process construction counter — two planes in one process get
    #: distinct collective names (same contract as FlatSharder).
    _instances = 0

    def __init__(self, unit_params: Sequence[Sequence], *,
                 name: str = "fsdp", prefetch: Optional[int] = None,
                 wire_dtype: Optional[str] = None,
                 average: bool = True):
        if not unit_params:
            raise ValueError("FsdpPlane needs at least one unit")
        self.name = name
        self._wire_name = f"fsdp.{name}.{FsdpPlane._instances}"
        FsdpPlane._instances += 1
        self.prefetch = (prefetch_default() if prefetch is None
                         else max(0, int(prefetch)))
        self.wire_dtype = wire_dtype
        self.average = average
        self.units: List[FsdpUnit] = []
        self._full: Dict[int, np.ndarray] = {}      # i -> full flat
        self._ag_handles: Dict[int, int] = {}       # i -> engine handle
        self._rs_handles: Dict[int, Tuple[int, dict]] = {}
        self._steps = 0
        total = 0
        for i, arrays in enumerate(unit_params):
            arrs = [np.asarray(a) for a in arrays]
            shapes = [tuple(a.shape) for a in arrs]
            flat = FlatSharder.flatten(arrs, np.float32)
            n = int(flat.size)
            if n == 0:
                raise ValueError(f"FSDP unit {i} has no parameters")
            sharder = FlatSharder(n, np.float32,
                                  name=f"{self._wire_name}.u{i}")
            shard = flat[sharder.offset:sharder.offset + sharder.count] \
                .copy()
            self.units.append(FsdpUnit(i, f"{name}.u{i}", shapes, n,
                                       sharder, shard))
            total += n * 4
            _note_resident(shard.nbytes)
        self.total_param_bytes = total
        _note_units(len(self.units))

    # -- geometry --

    @property
    def n_units(self) -> int:
        return len(self.units)

    @property
    def shard_bytes(self) -> int:
        return sum(u.shard.nbytes for u in self.units)

    def shard(self, i: int) -> np.ndarray:
        """This rank's owned fp32 window of unit ``i`` (mutable: the
        optimizer updates it in place; the next gather serves the new
        bytes)."""
        return self.units[i].shard

    def check_world(self) -> None:
        """Raise :class:`ShardResizeError` when the committed world size
        changed under the plane (elastic resize) — param shards live
        only on their owner, so continuing would corrupt the model.
        Re-anchors silently on a same-size epoch bump."""
        for u in self.units:
            u.sharder.check_world()

    # -- forward: just-in-time allgather + band-0 prefetch --

    def start_gather(self, i: int, *, priority: Optional[int] = 0) -> None:
        """Enqueue unit ``i``'s parameter allgather (idempotent: a
        pending handle or an already-gathered unit is left alone).
        ``priority=0`` is the prefetch band — most urgent, so the banded
        scheduler dispatches it ahead of same-cycle bulk traffic."""
        if i < 0 or i >= len(self.units) or i in self._full \
                or i in self._ag_handles:
            return
        u = self.units[i]
        u.sharder.check_world()
        eng = engine_or_none()
        if eng is None:
            return
        self._ag_handles[i] = eng.enqueue_allgather(
            u.shard, name=f"{self._wire_name}.u{i}.ag",
            priority=priority)

    def gather(self, i: int, *, direction: int = 1) -> List[np.ndarray]:
        """Unit ``i``'s FULL params (list of arrays shaped like the
        originals — views of one gathered flat buffer), allgathered
        just-in-time, with the next ``prefetch`` units in traversal
        ``direction`` enqueued at band 0.  Counts a prefetch hit when a
        pending gather had already completed, a miss when it blocked
        (or was never enqueued)."""
        u = self.units[i]
        if i not in self._full:
            eng = engine_or_none()
            handle = self._ag_handles.pop(i, None)
            if eng is None:
                flat = u.shard.copy() if u.sharder.size == 1 else None
                if flat is None:
                    raise RuntimeError(
                        "FsdpPlane.gather without a running engine in a "
                        "multi-process world")
            else:
                if handle is None:
                    _note_prefetch(False)
                    u.sharder.check_world()
                    handle = eng.enqueue_allgather(
                        u.shard, name=f"{self._wire_name}.u{i}.ag",
                        priority=0)
                else:
                    _note_prefetch(eng.poll(handle))
                flat = np.asarray(eng.synchronize(handle))
            self._full[i] = flat
            _note_resident(flat.nbytes)
        for d in range(1, self.prefetch + 1):
            self.start_gather(i + direction * d, priority=0)
        return FlatSharder.unflatten(self._full[i], u.shapes)

    def free(self, i: int) -> None:
        """Drop unit ``i``'s gathered full params (the owned shard
        stays — it IS the parameter storage)."""
        flat = self._full.pop(i, None)
        if flat is not None:
            _note_resident(-flat.nbytes)

    def free_all(self) -> None:
        for i in list(self._full):
            self.free(i)

    # -- backward: async reduce-scatter the moment a unit's vjp lands --

    def reduce_grads(self, i: int, grads: Sequence, *,
                     priority: Optional[int] = None) -> None:
        """Enqueue unit ``i``'s gradient reducescatter NOW (the backward
        cascade: call as each unit's vjp completes, typically in reverse
        unit order).  ``priority`` defaults to the unit index — earlier
        units are needed first by the next forward, so they get the more
        urgent band.  Results are claimed by :meth:`wait_grads`."""
        if i in self._rs_handles:
            raise RuntimeError(
                f"unit {i} already has a gradient reduction in flight "
                "(wait_grads it first)")
        u = self.units[i]
        u.sharder.check_world()
        flat = FlatSharder.flatten([np.asarray(g) for g in grads],
                                   np.float32)
        if flat.size != u.n:
            raise ValueError(
                f"unit {i}: flat gradient length {flat.size} != {u.n}")
        eng = engine_or_none()
        if eng is None:
            shard = flat[u.sharder.offset:
                         u.sharder.offset + u.sharder.count].copy()
            self._rs_handles[i] = (-1, {"local": shard})
            return
        info: dict = {}
        handle = eng.enqueue_reducescatter(
            flat, name=f"{self._wire_name}.u{i}.rs",
            wire_dtype=self.wire_dtype,
            priority=i if priority is None else priority)
        self._rs_handles[i] = (handle, info)

    def wait_grads(self, i: int) -> np.ndarray:
        """Drain unit ``i``'s reducescatter: this rank's grad shard
        (length = owned window), divisor-correct under backup-worker
        partial commits.  A :class:`StepSkipped` partial commit that
        left this rank out re-raises AFTER the handle is cleaned up —
        nothing is stranded, and the prefetch pipeline keeps its state
        (parameter allgathers are full-world collectives, never
        partially committed)."""
        entry = self._rs_handles.pop(i, None)
        if entry is None:
            raise RuntimeError(f"unit {i} has no gradient reduction in "
                               "flight (reduce_grads it first)")
        handle, info = entry
        if handle == -1:  # world of one
            return info["local"]
        eng = engine_or_none()
        out = eng.synchronize(handle, info)
        if self.average:
            out = eng._apply_average(out,
                                     info.get("participants") or None)
        return out

    def pending_grads(self) -> List[int]:
        """Unit indices with a gradient reduction still in flight."""
        return sorted(self._rs_handles)

    def drain(self) -> Dict[int, BaseException]:
        """Drain EVERY in-flight handle (grad RS and prefetched AG),
        never abandoning one (an abandoned handle leaks its kept-alive
        buffer and leaves its name in flight — the engine drain-hygiene
        contract).  Returns ``{unit: error}`` for reductions that
        failed (e.g. StepSkipped); gathered params are cached as usual.
        Call when abandoning a step (a skipped rank) so the next step
        starts clean."""
        errs: Dict[int, BaseException] = {}
        eng = engine_or_none()
        for i in sorted(self._rs_handles):
            handle, info = self._rs_handles.pop(i)
            if handle == -1:
                continue
            try:
                eng.synchronize(handle, info)
            except BaseException as e:  # noqa: BLE001 — reported per unit
                errs[i] = e
        for i in sorted(self._ag_handles):
            handle = self._ag_handles.pop(i)
            try:
                flat = np.asarray(eng.synchronize(handle))
            except BaseException as e:  # noqa: BLE001 — reported per unit
                errs[i] = e
            else:
                self._full[i] = flat
                _note_resident(flat.nbytes)
        return errs

    def step(self) -> None:
        """Mark a completed FSDP step (the shared ``sharded_steps``
        counter) and verify nothing was left in flight."""
        if self._rs_handles:
            raise RuntimeError(
                f"FSDP step completed with gradient reductions still in "
                f"flight for units {sorted(self._rs_handles)}")
        self._steps += 1
        note_sharded_step()

    # -- checkpoint integration (writer speaks flat windows natively) --

    def sharded_state(self) -> Dict[str, Tuple[np.ndarray, int]]:
        """``{name: (owned_shard, n)}`` for ``CheckpointWriter.save(...,
        sharded=...)`` — each rank writes its owned windows directly, no
        gather-to-full; the manifest's per-leaf flat-offset windows
        express the layout, so a restore at ANY world size reassembles
        exactly (loader.my_flat_shard)."""
        return {f"fsdp.{self.name}.u{u.index}": (u.shard, u.n)
                for u in self.units}

    def restore(self, loader) -> None:
        """Load every unit's owned window from a checkpoint written at
        ANY world size (the loader's flat-offset resharding core)."""
        for u in self.units:
            got = loader.my_flat_shard(f"fsdp.{self.name}.u{u.index}",
                                       u.sharder.rank, u.sharder.size)
            if got.size != u.shard.size:
                raise ShardResizeError(
                    f"restored window for unit {u.index} has "
                    f"{got.size} elements, expected {u.shard.size}")
            u.shard[:] = np.asarray(got, dtype=np.float32)
        self.free_all()
