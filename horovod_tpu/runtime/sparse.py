"""Top-k sparse allreduce with error feedback over the engine's
allgather wire.

The Deep-Gradient-Compression / 1-bit-SGD line (Lin et al. 2018; Seide
et al. 2014): each rank sends only its k largest-magnitude gradient
entries and ACCUMULATES everything it did not send into a per-tensor
residual buffer, which is added back into the next step's gradient —
so small gradients are delayed, never lost, and convergence tracks the
dense run while wire bytes drop by ~1/ratio.

Wire mechanics: the selected ``(indices, values)`` ride the engine's
negotiated-dim-0 ALLGATHER path (the same machinery the torch sparse
gradient path uses), and every rank scatters-adds the gathered
contributions into a dense output.  Two allgathers of ``k`` entries
replace one dense allreduce of ``n`` elements.

Residual lifecycle: every residual is stamped with the membership epoch
it was accumulated under.  An elastic resize or abort-recovery bumps the
epoch (a re-rendezvous commit), and the next sparse allreduce RESETS any
stale-epoch residual to zeros — a dead incarnation's unsent gradient
fragments can never leak into the new world's updates (they belong to a
different set of peers and a different parameter state).

Determinism: selection is top-k by |value| with a seeded tie-break
(``HOROVOD_TOPK_SEED``, default 0): ties in magnitude are broken by a
seed-derived permutation of the indices, so same-world runs reproduce
exactly and different seeds decorrelate tie patterns across layers.

Deliberately jax-free (numpy + the native engine), like runtime.engine.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, Optional, Tuple

import numpy as np

from horovod_tpu.runtime import engine_or_none
from horovod_tpu.runtime.engine import note_sparse_allreduce

__all__ = ["sparse_allreduce_topk", "reset_residuals", "residual_norm",
           "default_topk_ratio"]


def default_topk_ratio() -> float:
    """The HOROVOD_SPARSE_TOPK env default (fraction of entries sent)."""
    raw = os.environ.get("HOROVOD_SPARSE_TOPK", "")
    try:
        v = float(raw) if raw else 0.01
    except ValueError:
        v = 0.01
    return min(1.0, max(1e-6, v))


#: name -> (epoch, residual) — the per-tensor error-feedback state.
_RESIDUALS: Dict[str, Tuple[int, np.ndarray]] = {}
_LOCK = threading.Lock()

_TIE_PERM_CACHE: Dict[Tuple[int, int], np.ndarray] = {}


def _tie_perm(n: int) -> np.ndarray:
    """Seeded permutation used as the top-k tie-break key (cached per
    (seed, n): regenerating a multi-million-entry permutation per step
    would dominate selection time)."""
    seed = int(os.environ.get("HOROVOD_TOPK_SEED", "0") or 0)
    key = (seed, n)
    perm = _TIE_PERM_CACHE.get(key)
    if perm is None:
        perm = np.random.default_rng(seed).permutation(n)
        if len(_TIE_PERM_CACHE) > 64:
            _TIE_PERM_CACHE.clear()
        _TIE_PERM_CACHE[key] = perm
    return perm


def reset_residuals(name: Optional[str] = None) -> None:
    """Drop error-feedback residuals (all of them, or one tensor's).
    Epoch stamping already clears residuals on elastic resize; this is
    the explicit hook for a fresh training run in the same process."""
    with _LOCK:
        if name is None:
            _RESIDUALS.clear()
        else:
            _RESIDUALS.pop(name, None)


def residual_norm(name: str) -> float:
    """L2 norm of a tensor's current residual (0.0 when none) — test and
    debugging surface for 'the residuals are load-bearing'."""
    with _LOCK:
        entry = _RESIDUALS.get(name)
    return float(np.linalg.norm(entry[1])) if entry is not None else 0.0


def sparse_allreduce_topk(tensor, *, name: str,
                          ratio: Optional[float] = None,
                          error_feedback: bool = True,
                          average: bool = True) -> np.ndarray:
    """Dense-in dense-out top-k sparse allreduce (SUM or mean) of a
    float array; see the module docstring for semantics.

    ``name`` is REQUIRED (it keys the residual buffer and the wire
    rendezvous — per gradient leaf, like every collective name).
    """
    eng = engine_or_none()
    arr = np.ascontiguousarray(tensor, dtype=np.float32)
    shape = arr.shape
    flat = arr.reshape(-1)
    n = flat.size
    if n == 0:
        return arr
    if ratio is None:
        ratio = default_topk_ratio()
    k = max(1, min(n, int(round(n * ratio))))
    # World of one: the wire is an identity but the SEMANTICS (top-k
    # selection + residual accumulation) still apply, so code paths are
    # identical at any scale — same contract as eager.allreduce.
    epoch = eng.epoch() if eng is not None else 0

    with _LOCK:
        entry = _RESIDUALS.get(name) if error_feedback else None
    if entry is not None and entry[0] == epoch and entry[1].size == n:
        v = flat + entry[1]
    else:
        # First use, feedback off, or a stale-epoch/resized residual
        # from a previous incarnation of the world: start clean.
        v = flat.copy()

    # Deterministic top-k: primary key |v| descending, tie-break by the
    # seeded permutation (argpartition alone is unordered on ties, which
    # would make same-world reruns diverge at equal magnitudes).
    absv = np.abs(v)
    if k < n:
        # Cheap pre-cut, then an exact order among the candidates.
        cand = np.argpartition(absv, n - k)[n - k:]
        order = np.lexsort((_tie_perm(n)[cand], -absv[cand]))
        sel = cand[order[:k]]
    else:
        sel = np.arange(n)
    sel = np.ascontiguousarray(sel, dtype=np.int64)
    vals = np.ascontiguousarray(v[sel], dtype=np.float32)

    if error_feedback:
        residual = v.copy()
        residual[sel] = 0.0
        with _LOCK:
            _RESIDUALS[name] = (epoch, residual)

    # indices + values ride the negotiated-dim-0 allgather path; k can
    # legitimately differ per rank (callers may pass different ratios),
    # the wire negotiates each rank's dim-0.
    if eng is not None:
        from horovod_tpu.common.basics import basics

        h_idx = eng.enqueue_allgather(sel, name=f"{name}.topk_idx")
        h_val = eng.enqueue_allgather(vals, name=f"{name}.topk_val")
        idx_all = eng.synchronize(h_idx)
        val_all = eng.synchronize(h_val)
        world = basics.size()
    else:
        idx_all, val_all, world = sel, vals, 1

    out = np.zeros(n, dtype=np.float64)
    np.add.at(out, idx_all, val_all.astype(np.float64))
    if average:
        out /= world
    note_sparse_allreduce()
    return out.astype(np.float32).reshape(shape)
