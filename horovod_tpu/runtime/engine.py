"""ctypes bridge to the native engine (eager cross-process collectives).

Reference parity: the Python↔C seam of the reference — op libraries calling
``EnqueueTensorAllreduce/Allgather/Broadcast`` and the torch handle API
(``poll``/``synchronize``, horovod/torch/mpi_ops.py:406-438) — merged into
one handle-based surface:

* ``enqueue_*`` → int handle (async; the background coordinator negotiates
  readiness across processes and executes fused ring collectives)
* ``poll(handle)`` / ``synchronize(handle)``
* sync wrappers ``allreduce/allgather/broadcast`` = enqueue + synchronize.

Works on host numpy buffers; the JAX/torch layers convert at their edges.
This module deliberately does NOT import jax — the torch frontend and the
multi-process tests use it standalone.
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import Optional

import numpy as np

__all__ = ["NativeEngine", "get_engine", "HorovodInternalError",
           "SparseGradRetry", "StepSkipped"]


class HorovodInternalError(RuntimeError):
    """A collective failed (cross-rank mismatch, shutdown, transport)."""


class StepSkipped(Exception):
    """A backup-worker partial commit (``HOROVOD_BACKUP_WORKERS``) left
    this rank out of a step's reduction: the survivors committed without
    its gradient and this rank's entry completed with the clean
    "skipped this step" status — NOT an abort.  The world is healthy;
    the caller should skip (or keep local) this step's update and
    continue, re-syncing parameters periodically (local-SGD sync,
    ``ElasticState.sync`` or a broadcast) to bound drift."""


class SparseGradRetry(Exception):
    """A layout-probe allreduce was told by the coordinator that peers are
    gathering this tensor SPARSELY: the caller must re-enqueue zero-entry
    sparse gathers ('<name>.idx' / '<name>.vals').  Raised only for
    handles created by :meth:`NativeEngine.enqueue_probe`."""

    def __init__(self, sparse_dim: int):
        super().__init__(f"retry sparsely (sparse_dim={sparse_dim})")
        self.sparse_dim = sparse_dim


_SPARSE_RETRY_PREFIX = "__sparse_retry__:"
_SKIPPED_STEP_PREFIX = "__skipped_step__"


# DataType codes, keep in sync with cpp/common.h.
_DTYPE_CODES = {
    "uint8": 0,
    "int8": 1,
    "uint16": 2,
    "int16": 3,
    "int32": 4,
    "int64": 5,
    "float16": 6,
    "float32": 7,
    "float64": 8,
    "bool": 9,
    "bfloat16": 10,
}

_OP_ALLREDUCE, _OP_ALLGATHER, _OP_BROADCAST = 0, 1, 2
_OP_REDUCESCATTER, _OP_ALLTOALL = 3, 4

#: ReduceOp codes, keep in sync with cpp/message.h.
_RED_OPS = {"sum": 0, "min": 1, "max": 2, "prod": 3}

#: WireDtype codes, keep in sync with cpp/common.h (negotiated wire
#: format for fp32 allreduce payloads; fp32 = uncompressed default).
WIRE_DTYPES = {"fp32": 0, "fp16": 1, "bf16": 2, "int8": 3, "fp8": 4}
_WIRE_NAMES = {v: k for k, v in WIRE_DTYPES.items()}

#: Python-side counter for top-k sparse allreduces (the sparse path
#: rides the engine's allgather wire; the engine itself cannot tell a
#: sparse gather from any other).  Cumulative like the C counters, so
#: stats_delta() handles it transparently.
_SPARSE_COUNT = 0


def note_sparse_allreduce() -> None:
    """Called by runtime.sparse once per completed sparse allreduce."""
    global _SPARSE_COUNT
    _SPARSE_COUNT += 1


def note_local_sgd_sync() -> None:
    """Called by the local-SGD policy (elastic.state.LocalSGD) once per
    completed outer delta sync — lands in the engine's cumulative
    ``local_sgd_syncs`` counter (no-op when no engine is loaded)."""
    global _engine
    eng = _engine
    if eng is None:
        return
    fn = getattr(eng._lib, "horovod_note_local_sgd_sync", None)
    if fn is not None and getattr(fn, "restype", "?") is None:
        fn()


def note_sharded_step() -> None:
    """Called by the sharded optimizers (runtime.sharded) once per
    completed ZeRO step (reducescatter → shard update → allgather) —
    lands in the engine's cumulative ``sharded_steps`` counter (no-op
    when no engine is loaded or against a stale prebuilt .so)."""
    global _engine
    eng = _engine
    if eng is None:
        return
    fn = getattr(eng._lib, "horovod_note_sharded_step", None)
    if fn is not None and getattr(fn, "restype", "?") is None:
        fn()


def note_moe_dispatch(dropped: int) -> None:
    """Called by the MoE plane (runtime.moe) once per completed
    dispatch/combine round with the number of capacity-dropped tokens —
    lands in the engine's cumulative ``moe_tokens_dropped`` counter so
    drop accounting rides the TELEM fleet aggregation (no-op when no
    engine is loaded or against a stale prebuilt .so)."""
    global _engine
    eng = _engine
    if eng is None:
        return
    fn = getattr(eng._lib, "horovod_note_moe_dispatch", None)
    if fn is not None and getattr(fn, "restype", "?") is None:
        fn(int(dropped))


def flight_note(kind: str, text: str) -> None:
    """Record a Python-plane event (e.g. a checkpoint commit/restore)
    into the C++ flight recorder's ring, so postmortem merges it into
    the same timeline as aborts and link events (no-op when no engine
    is loaded or against a stale prebuilt .so)."""
    global _engine
    eng = _engine
    if eng is None:
        return
    fn = getattr(eng._lib, "horovod_flight_note", None)
    if fn is not None and getattr(fn, "restype", "?") is None:
        fn(str(kind).encode()[:15], str(text).encode()[:160])


def _checkpoint_stats() -> dict:
    """The checkpoint plane's stats() slice (lazy import: the plane
    imports this module for its commit barrier)."""
    from horovod_tpu.checkpoint.stats import checkpoint_stats

    return checkpoint_stats()


def _fsdp_stats() -> dict:
    """The FSDP plane's stats() slice (lazy import: the plane imports
    this module for its collectives, like the checkpoint plane)."""
    from horovod_tpu.runtime.fsdp import fsdp_stats

    return fsdp_stats()


def _moe_stats() -> dict:
    """The MoE plane's stats() slice (lazy import, like the FSDP
    plane's)."""
    from horovod_tpu.runtime.moe import moe_stats

    return moe_stats()


def _dtype_code(dtype) -> int:
    name = np.dtype(dtype).name if np.dtype(dtype).name in _DTYPE_CODES \
        else str(dtype)
    try:
        return _DTYPE_CODES[name]
    except KeyError:
        raise TypeError(f"unsupported dtype for native collectives: {dtype}")


class NativeEngine:
    """Wraps the loaded ``libhorovod_core.so``."""

    def __init__(self, lib: ctypes.CDLL):
        self._lib = lib
        self._declare(lib)
        self._name_lock = threading.Lock()
        self._name_counters: dict[str, int] = {}
        # Keep buffers alive while their collective is in flight
        # (reference _handle_map, torch/mpi_ops.py:51-54).
        self._inflight: dict[int, np.ndarray] = {}
        self._inflight_lock = threading.Lock()

    @staticmethod
    def _declare(lib: ctypes.CDLL) -> None:
        lib.horovod_enqueue.argtypes = [
            ctypes.c_int, ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_void_p, ctypes.c_int,
            ctypes.c_int,
        ]
        lib.horovod_enqueue.restype = ctypes.c_int64
        try:
            lib.horovod_enqueue_wire.argtypes = [
                ctypes.c_int, ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
                ctypes.POINTER(ctypes.c_int64), ctypes.c_void_p,
                ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ]
            lib.horovod_enqueue_wire.restype = ctypes.c_int64
        except AttributeError:
            pass  # stale .so: per-tensor wire overrides raise in _enqueue
        try:
            lib.horovod_enqueue_priority.argtypes = [
                ctypes.c_int, ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
                ctypes.POINTER(ctypes.c_int64), ctypes.c_void_p,
                ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
                ctypes.c_int,
            ]
            lib.horovod_enqueue_priority.restype = ctypes.c_int64
        except AttributeError:
            pass  # stale .so: priority enqueues raise in _enqueue
        lib.horovod_enqueue_probe.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_void_p,
        ]
        lib.horovod_enqueue_probe.restype = ctypes.c_int64
        lib.horovod_poll.argtypes = [ctypes.c_int64]
        lib.horovod_poll.restype = ctypes.c_int
        lib.horovod_wait.argtypes = [ctypes.c_int64]
        lib.horovod_wait.restype = ctypes.c_int
        lib.horovod_error_message.argtypes = [
            ctypes.c_int64, ctypes.c_char_p, ctypes.c_int,
        ]
        lib.horovod_error_message.restype = None
        lib.horovod_result_ndim.argtypes = [ctypes.c_int64]
        lib.horovod_result_ndim.restype = ctypes.c_int64
        lib.horovod_result_dim.argtypes = [ctypes.c_int64, ctypes.c_int]
        lib.horovod_result_dim.restype = ctypes.c_int64
        lib.horovod_result_bytes.argtypes = [ctypes.c_int64]
        lib.horovod_result_bytes.restype = ctypes.c_int64
        lib.horovod_copy_result.argtypes = [
            ctypes.c_int64, ctypes.c_void_p, ctypes.c_int64,
        ]
        lib.horovod_copy_result.restype = ctypes.c_int
        lib.horovod_release_handle.argtypes = [ctypes.c_int64]
        lib.horovod_release_handle.restype = None
        lib.horovod_size.restype = ctypes.c_int
        # Diagnostics-only counters: degrade (stats() raises a clear
        # rebuild hint) instead of hard-failing init against a stale
        # prebuilt .so that predates these symbols.
        try:
            for sym in ("horovod_exec_cycles",
                        "horovod_responses_executed",
                        "horovod_tensors_executed",
                        "horovod_cache_hits",
                        "horovod_cache_misses",
                        "horovod_cache_evictions",
                        "horovod_negotiation_bytes_tx",
                        "horovod_negotiation_bytes_rx",
                        "horovod_control_round_trips",
                        "horovod_stale_epoch_msgs",
                        "horovod_epoch",
                        "horovod_data_bytes_tx",
                        "horovod_data_bytes_rx",
                        "horovod_reduce_ns",
                        "horovod_wire_ns",
                        "horovod_allreduce_bytes",
                        "horovod_allreduce_ns",
                        "horovod_num_channels",
                        "horovod_chunk_bytes",
                        "horovod_fusion_threshold",
                        "horovod_cycle_time_ms",
                        "horovod_wave_width",
                        "horovod_channel_drivers",
                        "horovod_cache_capacity",
                        "horovod_socket_buf_bytes",
                        "horovod_shm_bytes_tx",
                        "horovod_shm_bytes_rx",
                        "horovod_intra_host_bytes",
                        "horovod_algo_small_count",
                        "horovod_algo_ring_count",
                        "horovod_topology_hosts",
                        "horovod_topology_local_ranks",
                        "horovod_shm_enabled",
                        "horovod_algo_threshold",
                        "horovod_wire_bytes_saved",
                        "horovod_compressed_bytes_tx",
                        "horovod_quantize_ns",
                        "horovod_wire_fp16_count",
                        "horovod_wire_bf16_count",
                        "horovod_wire_int8_count",
                        "horovod_wire_fp8_count",
                        "horovod_wire_dtype",
                        "horovod_assign_bytes_tx",
                        "horovod_coordinator_cycle_ns_p50",
                        "horovod_coordinator_cycle_ns_p99",
                        "horovod_hier_coordinator",
                        "horovod_backup_workers",
                        "horovod_backup_skips",
                        "horovod_local_sgd_syncs",
                        "horovod_step_time_ns_p50",
                        "horovod_step_time_ns_p99",
                        "horovod_backup_auto",
                        "horovod_backup_auto_ratio_milli",
                        "horovod_backup_armed",
                        "horovod_reducescatter_bytes",
                        "horovod_reducescatter_ns",
                        "horovod_reducescatter_fallbacks",
                        "horovod_sharded_steps",
                        "horovod_telemetry_cycles",
                        "horovod_telem_bytes_tx",
                        "horovod_stall_warnings",
                        "horovod_clock_offset_ns",
                        "horovod_quorum_lag_ns_p50",
                        "horovod_quorum_lag_ns_p99",
                        "horovod_backup_auto_rule",
                        "horovod_fleet_rows",
                        "horovod_flight_events",
                        "horovod_flight_dumps",
                        "horovod_link_reconnects",
                        "horovod_link_heal_failures",
                        "horovod_link_heal_ns_p50",
                        "horovod_link_heal_ns_p99",
                        "horovod_link_retries",
                        "horovod_link_heal_timeout_ms",
                        "horovod_priority_bands",
                        "horovod_priority_inversions",
                        "horovod_tune_trials"):
                fn = getattr(lib, sym)
                fn.argtypes = []
                fn.restype = ctypes.c_int64
        except AttributeError:
            pass  # stale .so: stats() raises the rebuild hint instead
        try:
            lib.horovod_abort_reason.argtypes = [
                ctypes.c_char_p, ctypes.c_int,
            ]
            lib.horovod_abort_reason.restype = None
        except AttributeError:
            pass  # stale .so: abort_reason() degrades to ""
        try:
            lib.horovod_result_participants.argtypes = [ctypes.c_int64]
            lib.horovod_result_participants.restype = ctypes.c_int64
            lib.horovod_note_local_sgd_sync.argtypes = []
            lib.horovod_note_local_sgd_sync.restype = None
        except AttributeError:
            pass  # stale .so: participants degrade to size-based division
        try:
            lib.horovod_note_sharded_step.argtypes = []
            lib.horovod_note_sharded_step.restype = None
        except AttributeError:
            pass  # stale .so: the sharded_steps counter stays 0
        try:
            for sym in ("horovod_alltoall_bytes",
                        "horovod_alltoall_ns",
                        "horovod_moe_tokens_dropped"):
                fn = getattr(lib, sym)
                fn.argtypes = []
                fn.restype = ctypes.c_int64
            lib.horovod_note_moe_dispatch.argtypes = [ctypes.c_int64]
            lib.horovod_note_moe_dispatch.restype = None
            lib.horovod_enqueue_alltoall.argtypes = [
                ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
                ctypes.POINTER(ctypes.c_int64), ctypes.c_void_p,
                ctypes.POINTER(ctypes.c_int64), ctypes.c_int,
                ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ]
            lib.horovod_enqueue_alltoall.restype = ctypes.c_int64
        except AttributeError:
            pass  # stale .so: splits alltoall raises; counters stay 0
        try:
            lib.horovod_autotune_set.argtypes = [
                ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
                ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
                ctypes.c_int64, ctypes.POINTER(ctypes.c_int64),
                ctypes.c_int, ctypes.c_int,
            ]
            lib.horovod_autotune_set.restype = ctypes.c_int
        except AttributeError:
            pass  # stale .so: the autotuner refuses to start
        try:
            lib.horovod_fleet_json.argtypes = [
                ctypes.c_char_p, ctypes.c_int64,
            ]
            lib.horovod_fleet_json.restype = ctypes.c_int64
            lib.horovod_flight_dump.argtypes = [ctypes.c_char_p]
            lib.horovod_flight_dump.restype = ctypes.c_int
        except AttributeError:
            pass  # stale .so: fleet_stats()/flight_dump() degrade
        try:
            lib.horovod_flight_note.argtypes = [
                ctypes.c_char_p, ctypes.c_char_p,
            ]
            lib.horovod_flight_note.restype = None
        except AttributeError:
            pass  # stale .so: checkpoint events skip the flight ring

    # -- naming (auto names must be identical across ranks, which holds when
    #    ranks enqueue in the same program order — same contract as the
    #    reference's op-name autogeneration) --

    def _auto_name(self, kind: str, name: Optional[str]) -> str:
        if name is not None:
            return name
        with self._name_lock:
            idx = self._name_counters.get(kind, 0)
            self._name_counters[kind] = idx + 1
        return f"{kind}.noname.{idx}"

    def reset_naming(self) -> None:
        """Reset the auto-name counters and drop stale in-flight buffer
        refs.  Called on shutdown (basics.shutdown) so a restarted
        engine's UNNAMED collectives count from zero again and rendezvous
        with freshly relaunched peers — otherwise an elastic recovery
        leaves survivors at 'allreduce.noname.N' while the replacement
        worker starts at '.noname.0' and nothing ever matches."""
        with self._name_lock:
            self._name_counters.clear()
        with self._inflight_lock:
            self._inflight.clear()

    # -- fault state --

    def abort_reason(self) -> str:
        """Why the engine aborted ("" while healthy / after clean
        shutdown) — e.g. which rank died, as diagnosed by the coordinator's
        failure detector."""
        if getattr(self._lib, "horovod_abort_reason", None) is None:
            return ""
        buf = ctypes.create_string_buffer(4096)
        self._lib.horovod_abort_reason(buf, len(buf))
        return buf.value.decode(errors="replace")

    def epoch(self) -> int:
        """The committed membership epoch: bumped by every successful
        rendezvous commit, so an elastic resize (shrink to survivors or a
        worker rejoin) increments it on every live member.  0 until the
        first init (or against a stale prebuilt .so)."""
        fn = getattr(self._lib, "horovod_epoch", None)
        if getattr(fn, "restype", None) is not ctypes.c_int64:
            return 0
        return int(fn())

    def _not_running_error(self) -> HorovodInternalError:
        reason = self.abort_reason()
        if reason:
            return HorovodInternalError(f"engine aborted: {reason}")
        return HorovodInternalError(
            "engine is not running (init not called or already shut down)"
        )

    # -- async enqueue API --

    def _stamp_priorities(self) -> bool:
        """Should enqueues carry their stamped priorities on the wire?
        True with priority bands committed on (the ordering consumes
        them) or under HOROVOD_PRIORITY_STAMP=1 (bench/tests measure the
        inversions counter with bands OFF).  False keeps the bands=0
        wire BYTE-IDENTICAL to the pre-priority protocol — the
        frontends stamp unconditionally and this one gate decides."""
        if os.environ.get("HOROVOD_PRIORITY_STAMP", "") not in ("", "0"):
            return True
        fn = getattr(self._lib, "horovod_priority_bands", None)
        if getattr(fn, "restype", None) is not ctypes.c_int64:
            return False
        return int(fn()) > 0

    def _enqueue(self, op: int, arr: np.ndarray, name: str,
                 root_rank: int = -1, red_op: str = "sum",
                 wire_dtype: Optional[str] = None,
                 priority: Optional[int] = None,
                 wire_advisory: bool = False) -> int:
        if priority is not None and not self._stamp_priorities():
            priority = None
        shape = (ctypes.c_int64 * arr.ndim)(*arr.shape)
        if wire_dtype is not None and wire_dtype not in WIRE_DTYPES:
            raise ValueError(
                f"unknown wire_dtype {wire_dtype!r} "
                f"(want one of {sorted(WIRE_DTYPES)})")
        if priority is not None or wire_advisory:
            fn = getattr(self._lib, "horovod_enqueue_priority", None)
            if getattr(fn, "restype", None) is not ctypes.c_int64:
                raise RuntimeError(
                    "libhorovod_core.so predates per-tensor priorities — "
                    "rebuild it with `make -C horovod_tpu/cpp`")
            handle = fn(
                op, name.encode(), _dtype_code(arr.dtype), arr.ndim, shape,
                arr.ctypes.data_as(ctypes.c_void_p), root_rank,
                _RED_OPS[red_op],
                -1 if wire_dtype is None else WIRE_DTYPES[wire_dtype],
                1 if wire_advisory else 0,
                0 if priority is None else max(0, int(priority)),
            )
        elif wire_dtype is not None:
            fn = getattr(self._lib, "horovod_enqueue_wire", None)
            if getattr(fn, "restype", None) is not ctypes.c_int64:
                raise RuntimeError(
                    "libhorovod_core.so predates per-tensor wire dtypes — "
                    "rebuild it with `make -C horovod_tpu/cpp`")
            handle = fn(
                op, name.encode(), _dtype_code(arr.dtype), arr.ndim, shape,
                arr.ctypes.data_as(ctypes.c_void_p), root_rank,
                _RED_OPS[red_op], WIRE_DTYPES[wire_dtype],
            )
        else:
            handle = self._lib.horovod_enqueue(
                op, name.encode(), _dtype_code(arr.dtype), arr.ndim, shape,
                arr.ctypes.data_as(ctypes.c_void_p), root_rank,
                _RED_OPS[red_op],
            )
        if handle == -1:
            raise HorovodInternalError(
                f"a collective named {name!r} is already in flight "
                "(duplicate name)"
            )
        if handle < 0:
            raise self._not_running_error()
        with self._inflight_lock:
            self._inflight[handle] = arr
        return handle

    def enqueue_allreduce(self, arr: np.ndarray,
                          name: Optional[str] = None,
                          red_op: str = "sum",
                          wire_dtype: Optional[str] = None,
                          priority: Optional[int] = None,
                          wire_advisory: bool = False) -> int:
        """In-place allreduce of a contiguous array (``red_op``:
        sum/min/max/prod).  ``wire_dtype`` (fp32/fp16/bf16/int8/fp8)
        overrides the HOROVOD_WIRE_DTYPE wire format for THIS tensor —
        fp32 payloads only; every rank must request the same format or
        negotiation fails cleanly (``wire_advisory=True`` relaxes that:
        the coordinator commits the first value instead — the seam the
        statistics-driven wire policy uses).  ``priority`` (>= 0; 0 =
        most urgent, the default) is the scheduling priority the
        priority-banded coordinator orders responses by
        (HOROVOD_PRIORITY_BANDS); every rank must stamp the same value.
        Returns handle."""
        return self._enqueue(
            _OP_ALLREDUCE, arr, self._auto_name("allreduce", name),
            red_op=red_op, wire_dtype=wire_dtype, priority=priority,
            wire_advisory=wire_advisory)

    def enqueue_allgather(self, arr: np.ndarray,
                          name: Optional[str] = None,
                          priority: Optional[int] = None) -> int:
        """Gather every rank's dim-0 slice (sizes may differ).
        ``priority`` as in :meth:`enqueue_allreduce` — the FSDP plane
        stamps band 0 on its just-in-time parameter prefetches so the
        banded scheduler dispatches them ahead of bulk traffic."""
        return self._enqueue(
            _OP_ALLGATHER, arr, self._auto_name("allgather", name),
            priority=priority)

    def enqueue_probe(self, arr: np.ndarray, name: str) -> int:
        """Layout-probe allreduce (sum) of placeholder zeros for a tensor
        with no local gradient.  Completes as a dense allreduce unless
        peers are gathering the tensor sparsely — then ``synchronize``
        raises :class:`SparseGradRetry` instead."""
        shape = (ctypes.c_int64 * arr.ndim)(*arr.shape)
        handle = self._lib.horovod_enqueue_probe(
            name.encode(), _dtype_code(arr.dtype), arr.ndim, shape,
            arr.ctypes.data_as(ctypes.c_void_p))
        if handle == -1:
            raise HorovodInternalError(
                f"a collective named {name!r} is already in flight "
                "(duplicate name)")
        if handle < 0:
            raise self._not_running_error()
        with self._inflight_lock:
            self._inflight[handle] = arr
        return handle

    def enqueue_broadcast(self, arr: np.ndarray, root_rank: int,
                          name: Optional[str] = None) -> int:
        return self._enqueue(
            _OP_BROADCAST, arr, self._auto_name("broadcast", name),
            root_rank=root_rank)

    def enqueue_reducescatter(self, arr: np.ndarray,
                              name: Optional[str] = None,
                              red_op: str = "sum",
                              wire_dtype: Optional[str] = None,
                              priority: Optional[int] = None) -> int:
        """Reduce across ranks (``red_op``: sum/min/max/prod), keep this
        rank's dim-0 slice (rows split as evenly as possible, earlier ranks
        take the remainder).  ``wire_dtype`` rides the allreduce codec
        seam (fp32 payloads only): fp16/bf16 run the half-staged RS half,
        int8/fp8 take the exact-parity fallback.  ``priority`` as in
        :meth:`enqueue_allreduce`."""
        return self._enqueue(
            _OP_REDUCESCATTER, arr, self._auto_name("reducescatter", name),
            red_op=red_op, wire_dtype=wire_dtype, priority=priority)

    def enqueue_alltoall(self, arr: np.ndarray,
                         name: Optional[str] = None,
                         splits=None,
                         wire_dtype: Optional[str] = None,
                         priority: Optional[int] = None) -> int:
        """Exchange dim-0 blocks: output block i came from rank i.

        ``splits`` (world-size entries of non-negative dim-0 row counts
        summing to ``arr.shape[0]``) is this rank's per-destination
        routing — the MoE dispatch surface; every rank's splits are
        validated cross-rank like the dim-0 allgather geometry, and rank
        j receives column j of the committed split matrix.  ``None``
        keeps the legacy equal-split contract (dim 0 divisible by world
        size).  ``wire_dtype`` rides the codec seam (fp32 payloads only:
        fp16/bf16 half staging, int8/fp8 per-block quantization of the
        routed activations — fp32 stays bitwise-verbatim).  ``priority``
        as in :meth:`enqueue_allreduce` — MoE routing traffic stamps
        band 0 so it preempts bulk gradient bands."""
        name = self._auto_name("alltoall", name)
        if splits is None and wire_dtype is None and priority is None:
            return self._enqueue(_OP_ALLTOALL, arr, name)
        if priority is not None and not self._stamp_priorities():
            priority = None
        if wire_dtype is not None and wire_dtype not in WIRE_DTYPES:
            raise ValueError(
                f"unknown wire_dtype {wire_dtype!r} "
                f"(want one of {sorted(WIRE_DTYPES)})")
        fn = getattr(self._lib, "horovod_enqueue_alltoall", None)
        if getattr(fn, "restype", None) is not ctypes.c_int64:
            raise RuntimeError(
                "libhorovod_core.so predates variable-split alltoall — "
                "rebuild it with `make -C horovod_tpu/cpp`")
        sp = [] if splits is None else [int(s) for s in splits]
        if sp:
            world = self._lib.horovod_size()
            if len(sp) != world:
                raise ValueError(
                    f"alltoall splits must have one entry per rank "
                    f"({world}); got {len(sp)}")
            if any(s < 0 for s in sp):
                raise ValueError("alltoall splits must be non-negative")
            rows = arr.shape[0] if arr.ndim > 0 else 0
            if sum(sp) != rows:
                raise ValueError(
                    f"alltoall splits sum to {sum(sp)} but dim 0 is "
                    f"{rows}")
        shape = (ctypes.c_int64 * arr.ndim)(*arr.shape)
        csp = (ctypes.c_int64 * max(1, len(sp)))(*(sp or [0]))
        handle = fn(
            name.encode(), _dtype_code(arr.dtype), arr.ndim, shape,
            arr.ctypes.data_as(ctypes.c_void_p), csp, len(sp),
            -1 if wire_dtype is None else WIRE_DTYPES[wire_dtype],
            0, 0 if priority is None else max(0, int(priority)))
        if handle == -1:
            raise HorovodInternalError(
                f"a collective named {name!r} is already in flight "
                "(duplicate name)")
        if handle < 0:
            raise self._not_running_error()
        with self._inflight_lock:
            self._inflight[handle] = arr
        return handle

    # -- execution stats --

    def stats(self) -> dict:
        """Cumulative execution + control-plane counters.

        Execution: negotiation ``cycles`` that executed work,
        ``responses`` executed (a fused batch counts once), ``tensors``
        executed.  ``tensors/responses > 1`` ⇒ fusion; a frontend
        batching N tensors into one cycle moves ``cycles`` by ~1
        instead of N.

        Control plane (response cache, HOROVOD_CACHE_CAPACITY):
        ``cache_hits``/``cache_misses`` count enqueues negotiated via a
        cache-slot bit vs. a full serialized request;
        ``cache_evictions`` counts slots invalidated (shape/dtype/op
        change, abort, capacity churn); ``negotiation_bytes_tx``/``_rx``
        sum control-frame bytes from this process's perspective; and
        ``control_round_trips`` counts coordinator exchanges that carried
        negotiation payload (idle heartbeats excluded) — divide its delta
        by the step count to verify steady state runs at ~1 round trip
        per step.

        Data plane (multi-channel rings, HOROVOD_NUM_CHANNELS):
        ``data_bytes_tx``/``_rx`` sum payload bytes this process moved
        over ring data sockets (all collective types, all channels);
        ``wire_ns`` is cumulative thread-time progressing data sockets
        and ``reduce_ns`` cumulative thread-time inside reduction
        kernels — both sum ACROSS channels, so either may exceed wall
        time when channels overlap (that's the point);
        ``allreduce_bytes``/``allreduce_ns`` sum ring-allreduce payload
        and wall time, and ``allreduce_bus_bw_bytes_per_sec`` is the
        derived cumulative bus bandwidth 2(N-1)/N · bytes / wall (the
        NCCL busbw convention — comparable across world sizes);
        ``num_channels`` is the committed per-edge channel fan-out.

        Shared memory / hierarchy (HOROVOD_SHM_DISABLE=0, the default):
        ``shm_bytes_tx``/``_rx`` sum payload bytes this process moved
        through shm rings (they also count into ``data_bytes_*`` — shm
        is a transport of the same data plane); ``intra_host_bytes``
        sums payload exchanged with co-located ranks (tx + rx);
        ``algo_small_count``/``algo_ring_count`` count allreduce
        responses executed via the latency star path vs. the bandwidth
        ring (HOROVOD_ALGO_THRESHOLD); ``topology`` is the committed
        host grouping as ``{"hosts": H, "local_ranks": L}`` (this
        rank's group size).

        Autotune (HOROVOD_AUTOTUNE): ``tune_trials`` counts TUNE frames
        applied on this rank (0 with autotuning off — the observable
        proof the default path never sees one), and ``config`` reports
        every EFFECTIVE knob value currently in force — post-tuning, not
        the env default (see docs/autotune.md)."""
        # Gate on the NEWEST counter symbol so a stale prebuilt .so raises
        # the rebuild hint instead of an AttributeError mid-dict.
        if getattr(getattr(self._lib, "horovod_alltoall_bytes",
                           None),
                   "restype", None) is not ctypes.c_int64:
            raise RuntimeError(
                "libhorovod_core.so predates the alltoall/MoE "
                "counters (and possibly earlier counter families) — "
                "rebuild it with `make -C horovod_tpu/cpp`")
        size = self._lib.horovod_size()
        ar_bytes = self._lib.horovod_allreduce_bytes()
        ar_ns = self._lib.horovod_allreduce_ns()
        bus_bw = 0.0
        if ar_ns > 0 and size > 1:
            bus_bw = (ar_bytes * 2.0 * (size - 1) / size) / (ar_ns / 1e9)
        rs_bytes = self._lib.horovod_reducescatter_bytes()
        rs_ns = self._lib.horovod_reducescatter_ns()
        rs_bus_bw = 0.0
        if rs_ns > 0 and size > 1:
            rs_bus_bw = (rs_bytes * 1.0 * (size - 1) / size) / (rs_ns / 1e9)
        a2a_bytes = self._lib.horovod_alltoall_bytes()
        a2a_ns = self._lib.horovod_alltoall_ns()
        a2a_bus_bw = 0.0
        if a2a_ns > 0 and size > 1:
            a2a_bus_bw = (a2a_bytes * 1.0 * (size - 1) / size) \
                / (a2a_ns / 1e9)
        return {
            "cycles": self._lib.horovod_exec_cycles(),
            "responses": self._lib.horovod_responses_executed(),
            "tensors": self._lib.horovod_tensors_executed(),
            "cache_hits": self._lib.horovod_cache_hits(),
            "cache_misses": self._lib.horovod_cache_misses(),
            "cache_evictions": self._lib.horovod_cache_evictions(),
            "negotiation_bytes_tx":
                self._lib.horovod_negotiation_bytes_tx(),
            "negotiation_bytes_rx":
                self._lib.horovod_negotiation_bytes_rx(),
            "control_round_trips":
                self._lib.horovod_control_round_trips(),
            "stale_epoch_msgs":
                self._lib.horovod_stale_epoch_msgs(),
            # Big-world control plane: rendezvous ASSIGN bytes this
            # coordinator sent (frame compaction metric), and the
            # coordinator's control-plane cycle time p50/p99 over a
            # sliding window of payload cycles (gather + negotiate +
            # distribute, execution excluded; 0 on workers) — cycle
            # latency is observable without the timeline.
            "assign_bytes_tx": self._lib.horovod_assign_bytes_tx(),
            "coordinator_cycle_ns_p50":
                self._lib.horovod_coordinator_cycle_ns_p50(),
            "coordinator_cycle_ns_p99":
                self._lib.horovod_coordinator_cycle_ns_p99(),
            # Straggler tolerance: allreduce completion-latency
            # percentiles (enqueue -> finish over a sliding window; one
            # slow rank inflates every participant's p99 at k=0 and
            # backup-worker commits pull it back down), partial commits
            # that left THIS rank out, and outer local-SGD syncs the
            # Python policy completed.
            "step_time_ns_p50": self._lib.horovod_step_time_ns_p50(),
            "step_time_ns_p99": self._lib.horovod_step_time_ns_p99(),
            # Fleet observability: coordinator quorum-lag percentiles
            # (how long the LAST voter trailed the second-to-last per
            # committed negotiation — the straggler instrument
            # backup=auto's default rule arms from), TELEM piggyback
            # bytes this rank sent, stall warnings emitted, and the
            # rendezvous-estimated monotonic clock offset to rank 0
            # (the merged timeline's alignment term).
            "quorum_lag_ns_p50": self._lib.horovod_quorum_lag_ns_p50(),
            "quorum_lag_ns_p99": self._lib.horovod_quorum_lag_ns_p99(),
            "telem_bytes_tx": self._lib.horovod_telem_bytes_tx(),
            "stall_warnings": self._lib.horovod_stall_warnings(),
            "clock_offset_ns": self._lib.horovod_clock_offset_ns(),
            "flight_events": self._lib.horovod_flight_events(),
            "flight_dumps": self._lib.horovod_flight_dumps(),
            "backup_skips": self._lib.horovod_backup_skips(),
            # Link self-healing (HOROVOD_LINK_RETRIES): data-channel
            # edges transparently re-established mid-collective, suspects
            # that exhausted the retry/deadline budget and escalated to
            # the unchanged abort path, and sliding-window percentiles of
            # suspect -> healed durations.  All provably zero under
            # HOROVOD_LINK_RETRIES=0.
            "link_reconnects": self._lib.horovod_link_reconnects(),
            "link_heal_failures":
                self._lib.horovod_link_heal_failures(),
            "link_heal_ns_p50": self._lib.horovod_link_heal_ns_p50(),
            "link_heal_ns_p99": self._lib.horovod_link_heal_ns_p99(),
            "local_sgd_syncs": self._lib.horovod_local_sgd_syncs(),
            # Priority scheduling (HOROVOD_PRIORITY_BANDS): committed
            # responses dispatched after a LESS-urgent response of the
            # same cycle — deterministic (dispatch-list order), nonzero
            # only when priorities are stamped and bands are off, and 0
            # by construction with bands on (the overlap ci gate
            # asserts it on the real-model loop).
            "priority_inversions":
                self._lib.horovod_priority_inversions(),
            "data_bytes_tx": self._lib.horovod_data_bytes_tx(),
            "data_bytes_rx": self._lib.horovod_data_bytes_rx(),
            "reduce_ns": self._lib.horovod_reduce_ns(),
            "wire_ns": self._lib.horovod_wire_ns(),
            "allreduce_bytes": ar_bytes,
            "allreduce_ns": ar_ns,
            "allreduce_bus_bw_bytes_per_sec": bus_bw,
            # Reduce-scatter (first-class collective; the ZeRO sharded
            # optimizer's gradient half): payload bytes / wall time of
            # RS responses, the derived bus bandwidth (N-1)/N·bytes/wall
            # — half the allreduce numerator, matching RS's wire
            # pattern — responses that took the exact-parity fallback
            # (full allreduce + local slice: unaligned multi-dim shards
            # or a block-quantized wire), and sharded-optimizer steps
            # completed on this process.
            "reducescatter_bytes": rs_bytes,
            "reducescatter_ns": rs_ns,
            "reducescatter_bus_bw_bytes_per_sec": rs_bus_bw,
            "reducescatter_fallbacks":
                self._lib.horovod_reducescatter_fallbacks(),
            "sharded_steps": self._lib.horovod_sharded_steps(),
            # Alltoall (first-class collective; the MoE plane's
            # dispatch/combine half): payload bytes / wall time of
            # ALLTOALL responses and the derived bus bandwidth
            # (N-1)/N·bytes/wall — matching the variable-split ring's
            # wire pattern — plus cumulative MoE drop-token accounting
            # (noted per dispatch from runtime/moe.py).
            "alltoall_bytes": a2a_bytes,
            "alltoall_ns": a2a_ns,
            "alltoall_bus_bw_bytes_per_sec": a2a_bus_bw,
            "moe_tokens_dropped":
                self._lib.horovod_moe_tokens_dropped(),
            "num_channels": self._lib.horovod_num_channels(),
            "shm_bytes_tx": self._lib.horovod_shm_bytes_tx(),
            "shm_bytes_rx": self._lib.horovod_shm_bytes_rx(),
            "intra_host_bytes": self._lib.horovod_intra_host_bytes(),
            "algo_small_count": self._lib.horovod_algo_small_count(),
            "algo_ring_count": self._lib.horovod_algo_ring_count(),
            # Wire compression (HOROVOD_WIRE_DTYPE / per-tensor wire
            # overrides): buffer-level bytes the wire representation
            # saved, compressed ring payload this rank sent, cumulative
            # (de)quantization time, allreduce responses per wire mode,
            # and top-k sparse allreduces completed on this process
            # (Python-side: the sparse path rides the allgather wire).
            "wire_bytes_saved": self._lib.horovod_wire_bytes_saved(),
            "compressed_bytes_tx":
                self._lib.horovod_compressed_bytes_tx(),
            "quantize_ns": self._lib.horovod_quantize_ns(),
            "wire_fp16_count": self._lib.horovod_wire_fp16_count(),
            "wire_bf16_count": self._lib.horovod_wire_bf16_count(),
            "wire_int8_count": self._lib.horovod_wire_int8_count(),
            "wire_fp8_count": self._lib.horovod_wire_fp8_count(),
            "sparse_count": _SPARSE_COUNT,
            # The checkpoint plane's counters (Python-side, like
            # sparse_count: the writer thread lives above the engine).
            **_checkpoint_stats(),
            # The FSDP plane's counters (Python-side: unit registry,
            # prefetch hit/miss, resident full-parameter bytes + peak).
            **_fsdp_stats(),
            # The MoE plane's counters (Python-side: dispatches
            # completed, configured capacity factor / expert gauges).
            **_moe_stats(),
            "topology": {
                "hosts": self._lib.horovod_topology_hosts(),
                "local_ranks": self._lib.horovod_topology_local_ranks(),
            },
            "tune_trials": self._lib.horovod_tune_trials(),
            "config": {
                "num_channels": self._lib.horovod_num_channels(),
                "channel_drivers": self._lib.horovod_channel_drivers(),
                "chunk_bytes": self._lib.horovod_chunk_bytes(),
                "fusion_threshold": self._lib.horovod_fusion_threshold(),
                "cycle_time_ms": self._lib.horovod_cycle_time_ms(),
                "wave_width": self._lib.horovod_wave_width(),
                "cache_capacity": self._lib.horovod_cache_capacity(),
                "socket_buf_bytes": self._lib.horovod_socket_buf_bytes(),
                "shm_enabled": bool(self._lib.horovod_shm_enabled()),
                "algo_threshold": self._lib.horovod_algo_threshold(),
                "hierarchical_coordinator":
                    bool(self._lib.horovod_hier_coordinator()),
                "wire_dtype": _WIRE_NAMES.get(
                    int(self._lib.horovod_wire_dtype()), "fp32"),
                "backup_workers": self._lib.horovod_backup_workers(),
                # HOROVOD_BACKUP_WORKERS=auto: the coordinator arms k=1
                # only while step_time_ns_p99/p50 exceeds
                # HOROVOD_BACKUP_AUTO_RATIO; `backup_armed` is its live
                # verdict (coordinator-evaluated; workers report False).
                "backup_auto": bool(self._lib.horovod_backup_auto()),
                "backup_auto_ratio":
                    self._lib.horovod_backup_auto_ratio_milli() / 1000.0,
                "backup_armed": bool(self._lib.horovod_backup_armed()),
                # backup=auto arming instrument: "quorum" (default —
                # per-entry quorum-lag percentiles) or "steptime" (the
                # legacy rank-0 completion-latency window,
                # HOROVOD_BACKUP_AUTO_RULE=steptime).
                "backup_auto_rule":
                    "steptime" if self._lib.horovod_backup_auto_rule()
                    else "quorum",
                # Link self-healing knobs (committed at rendezvous):
                # reconnect attempts per suspect edge (0 = healing off,
                # bit-for-bit the fail-fast engine) and the per-suspect
                # heal deadline.
                "link_retries": self._lib.horovod_link_retries(),
                "link_heal_timeout_ms":
                    self._lib.horovod_link_heal_timeout_ms(),
                # Priority band width (0 = off: legacy arrival ordering
                # bit-for-bit; committed at rendezvous, live-tunable).
                "priority_bands": self._lib.horovod_priority_bands(),
                # Fleet telemetry cadence (0 = off: control frames are
                # byte-identical to the pre-telemetry wire).
                "telemetry_cycles": self._lib.horovod_telemetry_cycles(),
            },
        }

    def stats_delta(self, since: dict) -> dict:
        """Counter deltas since a previous :meth:`stats` snapshot.

        Every cumulative counter comes back as ``now - since`` (a key
        missing from ``since`` counts from 0), with
        ``allreduce_bus_bw_bytes_per_sec`` recomputed FROM THE DELTA —
        the bandwidth of exactly the window between the two snapshots,
        which is what the autotuner scores trials with and what bench/
        tests previously hand-rolled.  Non-cumulative keys (``config``,
        ``num_channels``, ``topology``) carry the CURRENT value."""
        now = self.stats()
        delta: dict = {}
        for k, v in now.items():
            # Percentiles are sliding-window statistics, not cumulative
            # counters — carry the current value like config/topology.
            if k in ("config", "num_channels", "topology",
                     "allreduce_bus_bw_bytes_per_sec",
                     "reducescatter_bus_bw_bytes_per_sec",
                     "alltoall_bus_bw_bytes_per_sec",
                     # MoE gauges: configured capacity factor / expert
                     # count of the live plane — not cumulative.
                     "moe_capacity_factor",
                     "moe_experts",
                     "coordinator_cycle_ns_p50",
                     "coordinator_cycle_ns_p99",
                     "step_time_ns_p50",
                     "step_time_ns_p99",
                     "quorum_lag_ns_p50",
                     "quorum_lag_ns_p99",
                     "clock_offset_ns",
                     "checkpoint_ns_p50",
                     "checkpoint_ns_p99",
                     "last_checkpoint_step",
                     # FSDP gauges: units registered, bytes of full
                     # (gathered) params resident now, and the high-water
                     # mark — none are cumulative counters.
                     "fsdp_units",
                     "fsdp_param_bytes_resident",
                     "fsdp_param_bytes_resident_peak"):
                delta[k] = v
                continue
            delta[k] = v - since.get(k, 0)
        size = self._lib.horovod_size()
        bus_bw = 0.0
        if delta["allreduce_ns"] > 0 and size > 1:
            bus_bw = (delta["allreduce_bytes"] * 2.0 * (size - 1) / size) \
                / (delta["allreduce_ns"] / 1e9)
        delta["allreduce_bus_bw_bytes_per_sec"] = bus_bw
        rs_bw = 0.0
        if delta["reducescatter_ns"] > 0 and size > 1:
            rs_bw = (delta["reducescatter_bytes"] * 1.0 * (size - 1)
                     / size) / (delta["reducescatter_ns"] / 1e9)
        delta["reducescatter_bus_bw_bytes_per_sec"] = rs_bw
        a2a_bw = 0.0
        if delta["alltoall_ns"] > 0 and size > 1:
            a2a_bw = (delta["alltoall_bytes"] * 1.0 * (size - 1)
                      / size) / (delta["alltoall_ns"] / 1e9)
        delta["alltoall_bus_bw_bytes_per_sec"] = a2a_bw
        return delta

    def fleet_stats(self) -> dict:
        """Rank 0's fleet telemetry table (HOROVOD_TELEMETRY_CYCLES).

        Returns the aggregated per-rank (flat control plane) or per-host
        (hierarchical coordination) counter rows, fleet totals,
        slowest-rank attribution and quorum-lag percentiles as a dict —
        ``{}`` on workers, with telemetry off, or before the first TELEM
        frame arrived.  Counters are DELTAS summed on the coordinator,
        so a quiesced fleet's totals equal the sum of the per-rank
        :meth:`stats` values exactly (the observability ci gate asserts
        this on ``data_bytes_tx``).  Readable after shutdown too — the
        fleet table survives for post-mortem scrapes."""
        fn = getattr(self._lib, "horovod_fleet_json", None)
        if getattr(fn, "restype", None) is not ctypes.c_int64:
            return {}
        need = int(fn(None, 0))
        if need <= 2:  # "{}" — nothing reported yet
            return {}
        buf = ctypes.create_string_buffer(need + 1)
        fn(buf, need + 1)
        import json

        try:
            return json.loads(buf.value.decode(errors="replace"))
        except ValueError:
            return {}

    def flight_dump(self, reason: str = "manual dump") -> bool:
        """Dump the flight recorder to HOROVOD_FLIGHT_RECORDER_DIR now
        (``flightrec.rank<r>.json``); the engine also dumps on abort,
        stall-warning escalation, and fatal signals.  False when the
        recorder is disabled or has no dump directory."""
        fn = getattr(self._lib, "horovod_flight_dump", None)
        if getattr(fn, "restype", None) is not ctypes.c_int:
            return False
        return int(fn(reason.encode())) == 0

    def autotune_set(self, *, chunk_bytes: int = 0,
                     fusion_threshold: int = 0, cycle_time_ms: int = 0,
                     wave_width: int = 0, algo_threshold: int = -1,
                     wire_dtype: int = -1, priority_bands: int = -1,
                     fusion_ladder=None, commit: bool = False) -> bool:
        """Queue a TUNE proposal (coordinator only): the engine
        broadcasts it in the next cycle's epoch-stamped frame and every
        rank applies it between cycles.  Values <= 0 leave that knob
        unchanged — except ``algo_threshold``, ``wire_dtype`` and
        ``priority_bands``, where 0 is a real value (star path off /
        fp32 wire / bands off) and "leave unchanged" is < 0.
        ``fusion_ladder`` (sequence) sets band b's fusion threshold
        where the entry is > 0 (the autotuner's per-band bucket sizes).
        Returns False when the engine refused (not initialized, not the
        coordinator, or a stale prebuilt .so)."""
        fn = getattr(self._lib, "horovod_autotune_set", None)
        if getattr(fn, "restype", None) is not ctypes.c_int:
            return False
        # A stale prebuilt .so still EXPORTS horovod_autotune_set with
        # an older, shorter signature — extra args would land in the
        # wrong slots.  Gate on a symbol that only exists alongside the
        # priority-era signature (same discipline as the wire_dtype
        # extension before it).
        if getattr(getattr(self._lib, "horovod_priority_bands", None),
                   "restype", None) is not ctypes.c_int64:
            return False
        ladder = [int(v) for v in (fusion_ladder or [])]
        arr = (ctypes.c_int64 * max(1, len(ladder)))(*(ladder or [0]))
        return fn(int(chunk_bytes), int(fusion_threshold),
                  int(cycle_time_ms), int(wave_width), int(algo_threshold),
                  int(wire_dtype), int(priority_bands),
                  arr, len(ladder), 1 if commit else 0) == 0

    # -- handle API --

    def poll(self, handle: int) -> bool:
        """True once the collective finished (ok or error)."""
        return self._lib.horovod_poll(handle) != 0

    def synchronize(self, handle: int, info: Optional[dict] = None
                    ) -> np.ndarray:
        """Wait; raise on error; return the result buffer.

        For allreduce/broadcast this is the (in-place updated) input array;
        for allgather/reducescatter/alltoall it is a fresh array with the
        negotiated (possibly empty) shape.

        ``info`` (optional dict) receives ``participants``: how many
        ranks' data the committed response actually reduced — equal to
        size for a full commit, smaller for a backup-worker partial
        commit.  Divisor-correct averaging divides by it.

        Raises :class:`StepSkipped` when a backup-worker partial commit
        left this rank out (clean per-step outcome; the engine stays
        healthy).
        """
        status = self._lib.horovod_wait(handle)
        with self._inflight_lock:
            arr = self._inflight.pop(handle, None)
        try:
            if info is not None:
                fn = getattr(self._lib, "horovod_result_participants",
                             None)
                if getattr(fn, "restype", None) is ctypes.c_int64:
                    info["participants"] = int(fn(handle))
            if status < 0:
                buf = ctypes.create_string_buffer(4096)
                self._lib.horovod_error_message(handle, buf, len(buf))
                msg = buf.value.decode(errors="replace")
                if msg.startswith(_SPARSE_RETRY_PREFIX):
                    raise SparseGradRetry(
                        int(msg[len(_SPARSE_RETRY_PREFIX):]))
                if msg.startswith(_SKIPPED_STEP_PREFIX):
                    raise StepSkipped(msg)
                raise HorovodInternalError(msg or "collective failed")
            ndim = self._lib.horovod_result_ndim(handle)
            if ndim > 0:  # a fresh out-of-place result was negotiated
                shape = tuple(self._lib.horovod_result_dim(handle, i)
                              for i in range(ndim))
                out = np.empty(shape, dtype=arr.dtype)
                rc = self._lib.horovod_copy_result(
                    handle, out.ctypes.data_as(ctypes.c_void_p), out.nbytes)
                if rc != 0:
                    raise HorovodInternalError("result copy failed")
                return out
            return arr
        finally:
            self._lib.horovod_release_handle(handle)

    def drain(self, handles):
        """Synchronize EVERY handle of a batch, never abandoning one
        mid-drain (an abandoned handle leaks its kept-alive buffer and
        leaves its name "in flight", so a retry of the same batch after
        a recovery dies on duplicate names).  Returns
        ``(outs, infos, first_err)``: ``outs[i]`` is the result or None,
        ``infos[i]["participants"]`` the committed participant count,
        and ``first_err`` the first exception (None when all succeeded)
        — the caller re-raises or handles it AFTER the batch is clean.
        The shared drain-hygiene helper behind eager.grouped_allreduce,
        ElasticState.sync, LocalSGD.maybe_sync and the keras frontend."""
        outs, infos, first_err = [], [], None
        for h in handles:
            info: dict = {}
            try:
                outs.append(self.synchronize(h, info))
            except Exception as e:  # noqa: BLE001 — returned to caller
                if first_err is None:
                    first_err = e
                outs.append(None)
            infos.append(info)
        return outs, infos, first_err

    # -- sync convenience wrappers --

    def _apply_average(self, out: np.ndarray,
                       participants: Optional[int] = None) -> np.ndarray:
        """sum → average: floor-divide integers, true-divide floats.
        ``participants`` overrides the divisor (backup-worker partial
        commits reduce fewer than ``size`` contributions)."""
        n = participants or self._lib.horovod_size()
        if np.issubdtype(out.dtype, np.integer):
            return out // n
        return (out / np.asarray(n, dtype=out.dtype)).astype(out.dtype)

    def allreduce(self, tensor, *, average: bool = False,
                  name: Optional[str] = None,
                  red_op: str = "sum",
                  wire_dtype: Optional[str] = None,
                  priority: Optional[int] = None,
                  wire_advisory: bool = False) -> np.ndarray:
        arr = np.ascontiguousarray(tensor).copy()
        info: dict = {}
        out = self.synchronize(
            self.enqueue_allreduce(arr, name, red_op,
                                   wire_dtype=wire_dtype,
                                   priority=priority,
                                   wire_advisory=wire_advisory), info)
        if not average:
            return out
        return self._apply_average(out, info.get("participants") or None)

    def allgather(self, tensor, *, name: Optional[str] = None,
                  priority: Optional[int] = None) -> np.ndarray:
        arr = np.ascontiguousarray(tensor)
        if arr.ndim == 0:
            arr = arr.reshape(1)
        return self.synchronize(self.enqueue_allgather(arr, name,
                                                       priority=priority))

    def broadcast(self, tensor, root_rank: int,
                  *, name: Optional[str] = None) -> np.ndarray:
        arr = np.ascontiguousarray(tensor).copy()
        return self.synchronize(self.enqueue_broadcast(arr, root_rank, name))

    def reducescatter(self, tensor, *, average: bool = False,
                      name: Optional[str] = None,
                      red_op: str = "sum",
                      wire_dtype: Optional[str] = None) -> np.ndarray:
        arr = np.ascontiguousarray(tensor)
        info: dict = {}
        out = self.synchronize(
            self.enqueue_reducescatter(arr, name, red_op,
                                       wire_dtype=wire_dtype), info)
        if not average:
            return out
        return self._apply_average(out, info.get("participants") or None)

    def alltoall(self, tensor, *, name: Optional[str] = None,
                 splits=None, wire_dtype: Optional[str] = None,
                 priority: Optional[int] = None) -> np.ndarray:
        arr = np.ascontiguousarray(tensor)
        return self.synchronize(self.enqueue_alltoall(
            arr, name, splits=splits, wire_dtype=wire_dtype,
            priority=priority))


_engine: Optional[NativeEngine] = None
_engine_lock = threading.Lock()


def get_engine() -> NativeEngine:
    """The process-wide engine, bound to the lib loaded by HorovodBasics."""
    global _engine
    with _engine_lock:
        if _engine is None:
            from horovod_tpu.common.basics import basics

            lib = basics.native_lib
            if lib is None:
                raise RuntimeError(
                    "native engine library is not loaded; build it with "
                    "`make -C horovod_tpu/cpp` (required for cross-process "
                    "eager collectives)"
                )
            _engine = NativeEngine(lib)
        return _engine


def reset_engine_naming() -> None:
    """Reset the cached engine's auto-name counters (no-op when no engine
    was created).  Invoked by basics.shutdown() as part of the restart
    story — see NativeEngine.reset_naming."""
    with _engine_lock:
        if _engine is not None:
            _engine.reset_naming()
