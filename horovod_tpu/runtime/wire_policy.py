"""Per-tensor wire-dtype POLICY driven by gradient statistics.

The PR 8 wire-compression machinery negotiates one wire format per
tensor (``HOROVOD_WIRE_DTYPE`` global knob, or a per-tensor override).
A single global format is the wrong trade for real models: large
embedding-table gradients tolerate int8's per-chunk-scaled quantization
essentially for free (huge element counts, smooth magnitude
distributions), while norm/bias leaves are tiny (compressing them saves
nothing) and numerically load-bearing (they should stay fp32).

This module turns per-leaf rolling statistics into a DETERMINISTIC
per-tensor wire choice, stamped through the existing per-tensor
``wire_dtype`` override so the PR 8 negotiation/validation machinery is
reused unchanged:

* every leaf keeps a rolling (EWMA) abs-max and RMS of its gradient;
* 0/1-D leaves (biases, norms, scalars) and leaves below
  ``HOROVOD_WIRE_POLICY_MIN_ELEMS`` elements always stay ``fp32``;
* large multi-dim fp32 leaves (>= ``HOROVOD_WIRE_POLICY_MIN_ELEMS``
  elements — embedding/projection-shaped) switch to ``int8`` once the
  warmup has seen ``HOROVOD_WIRE_POLICY_WARMUP`` steps AND the observed
  dynamic range ``abs_max / rms`` stays under
  ``HOROVOD_WIRE_POLICY_RATIO`` (per-chunk scales absorb smooth ranges;
  a spiky leaf — rare huge outliers over a near-zero body — would lose
  them to quantization, so it stays fp32);
* everything else keeps the engine default.

Cross-rank safety: the statistics are PER-RANK, so two ranks can
legitimately disagree the step a leaf crosses the threshold.  Policy
wires are therefore stamped as ADVISORY overrides
(``Request::wire_default`` on the wire): the coordinator commits the
first value it sees instead of raising the strict mismatch error, every
rank executes the committed format, and the decisions converge within a
step — the exact mechanism PR 10 introduced for knob-derived wires
racing a live TUNE.

Enable with ``HOROVOD_WIRE_POLICY=1`` (the jax
``allreduce_gradients``/``DistributedOptimizer`` host path picks it up
automatically), or construct a :class:`WirePolicy` and pass it
explicitly.
"""

from __future__ import annotations

import math
import os
from typing import Dict, Optional

import numpy as np

__all__ = ["WirePolicy", "policy_enabled", "default_policy",
           "reset_default_policy"]


def _env_int(name: str, dflt: int) -> int:
    raw = os.environ.get(name, "")
    try:
        return int(raw) if raw else dflt
    except ValueError:
        return dflt


def _env_float(name: str, dflt: float) -> float:
    raw = os.environ.get(name, "")
    try:
        return float(raw) if raw else dflt
    except ValueError:
        return dflt


def policy_enabled(environ=os.environ) -> bool:
    return environ.get("HOROVOD_WIRE_POLICY", "") not in ("", "0")


class _LeafStats:
    __slots__ = ("abs_max", "rms", "steps")

    def __init__(self):
        self.abs_max = 0.0
        self.rms = 0.0
        self.steps = 0


class WirePolicy:
    """Deterministic per-leaf wire-dtype rule over rolling statistics.

    ``observe_and_choose(name, arr)`` updates the leaf's rolling abs-max
    / RMS and returns the wire dtype to stamp (``"int8"``, ``"fp32"``,
    or ``None`` = engine default).  Decisions are pure functions of the
    observed history — same gradients, same choices — and are meant to
    be stamped ADVISORY (see the module docstring).
    """

    def __init__(self, *, min_elems: Optional[int] = None,
                 ratio: Optional[float] = None,
                 warmup: Optional[int] = None,
                 decay: float = 0.9):
        self.min_elems = (_env_int("HOROVOD_WIRE_POLICY_MIN_ELEMS", 65536)
                          if min_elems is None else int(min_elems))
        self.ratio = (_env_float("HOROVOD_WIRE_POLICY_RATIO", 64.0)
                      if ratio is None else float(ratio))
        self.warmup = (_env_int("HOROVOD_WIRE_POLICY_WARMUP", 3)
                       if warmup is None else int(warmup))
        self.decay = float(decay)
        self._stats: Dict[str, _LeafStats] = {}
        #: name -> last stamped wire ("int8"/"fp32"/None); observability.
        self.decisions: Dict[str, Optional[str]] = {}

    def observe_and_choose(self, name: str,
                           arr: np.ndarray) -> Optional[str]:
        arr = np.asarray(arr)
        # Non-fp32 payloads never wire-compress (the engine forces fp32
        # wire for them anyway); skip the bookkeeping too.
        if arr.dtype != np.float32:
            self.decisions[name] = None
            return None
        # Norm/bias/scalar leaves (any 0/1-D leaf) and small leaves
        # (below min_elems, any rank): tiny and/or numerically
        # load-bearing — pin them to the uncompressed wire regardless of
        # the global knob.  (A live HOROVOD_WIRE_DTYPE=int8 would
        # otherwise drag them down with everything else.)
        if arr.ndim <= 1 or arr.size < self.min_elems:
            self.decisions[name] = "fp32"
            return "fp32"
        st = self._stats.get(name)
        if st is None:
            st = self._stats[name] = _LeafStats()
        a = float(np.max(np.abs(arr))) if arr.size else 0.0
        r = float(math.sqrt(float(np.mean(np.square(arr))))) \
            if arr.size else 0.0
        if st.steps == 0:
            st.abs_max, st.rms = a, r
        else:
            st.abs_max = self.decay * st.abs_max + (1 - self.decay) * a
            st.rms = self.decay * st.rms + (1 - self.decay) * r
        st.steps += 1
        wire: Optional[str] = None
        if (arr.ndim >= 2 and arr.size >= self.min_elems
                and st.steps > self.warmup and st.rms > 0.0
                and st.abs_max / st.rms <= self.ratio):
            # Embedding/projection-shaped, statistically smooth: the
            # per-chunk-scaled int8 wire quarters its bytes at fp32-
            # parity convergence (gated in ci).
            wire = "int8"
        self.decisions[name] = wire
        return wire

    def reset(self) -> None:
        self._stats.clear()
        self.decisions.clear()


_DEFAULT: Optional[WirePolicy] = None


def default_policy() -> WirePolicy:
    """The process-wide policy instance (env-configured)."""
    global _DEFAULT
    if _DEFAULT is None:
        _DEFAULT = WirePolicy()
    return _DEFAULT


def reset_default_policy() -> None:
    """Drop accumulated statistics (tests; engine restarts keep them —
    the statistics describe the MODEL, not the world incarnation)."""
    global _DEFAULT
    _DEFAULT = None
