"""Eager (host-driven) collectives across processes.

Reference parity: the enqueue → negotiate → execute pipeline
(``EnqueueTensorAllreduce`` → ``RunLoopOnce`` → ``PerformOperation``,
``horovod/common/operations.cc:2029-2145, 1694-1907, 714-1362``).

This module is the JAX-facing face of that pipeline.  At ``size() == 1``
the collectives are arithmetic identities (matching the reference under
``mpirun -np 1``), with averaging/compression semantics still applied so
code paths are identical at any scale.  At ``size() > 1`` calls go through
the native engine (``horovod_tpu/cpp`` via ``runtime.engine``): a rank-0
coordinator establishes a globally agreed, identically ordered, fused batch
of collectives per cycle — the reference's central correctness idea — and
executes them as ring collectives between the host processes.

Averaging happens here (SUM on the wire, divide on return); MIN/MAX/
PRODUCT ride the wire natively — an extension past the reference's
SUM-only protocol (``horovod/common/mpi_message.h``), matching the jit
path's psum/pmin/pmax/product surface.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax.numpy as jnp
import numpy as np

from horovod_tpu.common.basics import basics
from horovod_tpu.ops.collective_ops import (Average, Max, Min,
                                             Product, ReduceOp, Sum)
from horovod_tpu.ops.compression import Compression, TopKCompressor

__all__ = ["allreduce", "grouped_allreduce", "allgather", "broadcast",
           "reducescatter", "alltoall"]


def _resolve_op(op, average):
    if average is not None:
        return Average if average else Sum
    return op


#: collective_ops ReduceOp -> engine wire op name.
_WIRE_OPS = {Sum: "sum", Average: "sum", Min: "min", Max: "max",
             Product: "prod"}


from horovod_tpu.runtime import engine_or_none as _engine  # noqa: E402


def _topk_spec(compression) -> Optional[TopKCompressor]:
    return compression if isinstance(compression, TopKCompressor) else None


def _engine_wire(compression) -> Optional[str]:
    """A WireCompressor's engine wire dtype ("int8", ...), else None —
    the wire-level family compresses in the ENGINE (per-chunk-scaled
    quantized ring), not by casting the tensor."""
    wd = getattr(compression, "engine_wire_dtype", None)
    return wd if wd in ("fp16", "bf16", "int8", "fp8") else None


def allreduce(tensor, *, op=Average, average=None,
              compression=Compression.none, name: Optional[str] = None,
              priority: Optional[int] = None):
    op = _resolve_op(op, average)
    eng = _engine()
    arr = jnp.asarray(tensor)
    topk = _topk_spec(compression)
    if topk is not None:
        # Sparse top-k with error feedback: dense in, dense out, the
        # residual keyed by the collective name.  The name is REQUIRED:
        # auto-naming would mint a fresh name per call (residuals never
        # accumulate), and a shared default would cross-contaminate
        # different tensors' residual buffers — both silent corruption.
        from horovod_tpu.runtime import sparse

        if name is None:
            raise ValueError(
                "top-k sparse allreduce requires a stable per-tensor "
                "name= (it keys the error-feedback residual buffer)")
        if op not in (Average, Sum):
            raise NotImplementedError(
                "top-k sparse allreduce supports SUM/AVERAGE only")
        out = sparse.sparse_allreduce_topk(
            np.asarray(arr, dtype=np.float32), name=name,
            ratio=topk.ratio, error_feedback=topk.error_feedback,
            average=(op is Average))
        return jnp.asarray(out)
    wire, ctx = compression.compress(arr)
    if eng is None:
        return compression.decompress(wire, ctx)
    if op not in _WIRE_OPS:
        raise NotImplementedError(
            f"eager cross-process allreduce supports "
            f"SUM/AVERAGE/MIN/MAX/PRODUCT, got {op}"
        )
    host = np.ascontiguousarray(np.asarray(wire))
    reduced = eng.allreduce(host, average=(op is Average), name=name,
                            red_op=_WIRE_OPS[op],
                            wire_dtype=_engine_wire(compression),
                            priority=priority)
    return compression.decompress(jnp.asarray(reduced), ctx)


def grouped_allreduce(tensors: Sequence, *, op=Average, average=None,
                      compression=Compression.none,
                      name: Optional[str] = None,
                      priorities: Optional[Sequence[int]] = None,
                      wire_dtypes: Optional[Sequence] = None,
                      wire_advisory: bool = False):
    """Allreduce many tensors; cross-process they are enqueued together so
    the coordinator fuses them into few ring collectives
    (reference response fusion, operations.cc:1815-1842).

    ``priorities`` (one int per tensor, 0 = most urgent) stamps each
    tensor's scheduling priority for the priority-banded coordinator
    (HOROVOD_PRIORITY_BANDS); callers stamping from registration order
    pass ``range(len(tensors))``.  ``wire_dtypes`` (one entry per
    tensor, None = default) overrides the wire format per leaf — the
    statistics-driven wire policy's hookup — and ``wire_advisory=True``
    makes those overrides knob-like (the coordinator commits the first
    value on a cross-rank disagreement instead of erroring, which
    per-rank gradient statistics require)."""
    op = _resolve_op(op, average)
    eng = _engine()
    topk = _topk_spec(compression)
    if topk is not None:
        # Per-leaf residuals need stable per-tensor names; a default
        # base would collide across different grouped call sites and
        # cross-contaminate their residuals — require the name.
        if name is None:
            raise ValueError(
                "grouped top-k sparse allreduce requires name= (per-leaf "
                "residual buffers are keyed '<name>.<i>')")
        return [
            allreduce(t, op=op, compression=compression,
                      name=f"{name}.{i}")
            for i, t in enumerate(tensors)
        ]
    if eng is None:
        return [
            allreduce(t, op=op, compression=compression) for t in tensors
        ]
    if op not in _WIRE_OPS:
        raise NotImplementedError(
            "eager cross-process allreduce supports "
            f"SUM/AVERAGE/MIN/MAX/PRODUCT, got {op}"
        )
    if priorities is not None and len(priorities) != len(tensors):
        raise ValueError(
            f"{len(tensors)} tensors but {len(priorities)} priorities")
    if wire_dtypes is not None and len(wire_dtypes) != len(tensors):
        raise ValueError(
            f"{len(tensors)} tensors but {len(wire_dtypes)} wire_dtypes")
    ctxs, hosts = [], []
    for t in tensors:
        wire, ctx = compression.compress(jnp.asarray(t))
        ctxs.append(ctx)
        hosts.append(np.ascontiguousarray(np.asarray(wire)).copy())
    wd = _engine_wire(compression)
    # Per-leaf wire resolution: an explicit policy decision wins; a None
    # entry (policy undecided — warmup, mid-size leaf) falls back to the
    # compression-derived default, never silently to the global knob
    # (matching the torch frontend's fallback).
    def leaf_wire(i):
        if wire_dtypes is not None and wire_dtypes[i] is not None:
            return wire_dtypes[i], wire_advisory
        return wd, False

    handles = [
        eng.enqueue_allreduce(
            h, None if name is None else f"{name}.{i}",
            red_op=_WIRE_OPS[op],
            wire_dtype=leaf_wire(i)[0],
            priority=None if priorities is None else priorities[i],
            wire_advisory=leaf_wire(i)[1])
        for i, h in enumerate(hosts)
    ]
    # Drain EVERY handle even when one fails (eng.drain: abandoning the
    # rest would leak their buffers and leave names "in flight", so a
    # retry of the same batch after an elastic recovery would die on
    # duplicate names).  A StepSkipped (backup-worker partial commit
    # that left this rank out) counts as a failure of the batch: the
    # whole step's gradients are dropped together, and the caller skips
    # its local update.
    outs, infos, first_err = eng.drain(handles)
    if first_err is not None:
        raise first_err
    results = []
    for out, ctx, info in zip(outs, ctxs, infos):
        if op is Average:
            # Divisor-correct averaging: a backup-worker partial commit
            # reduced participants < size contributions.
            out = eng._apply_average(out,
                                     info.get("participants") or None)
        results.append(compression.decompress(jnp.asarray(out), ctx))
    return results


def allgather(tensor, *, name: Optional[str] = None):
    eng = _engine()
    if eng is None:
        return jnp.asarray(tensor)
    return jnp.asarray(eng.allgather(np.asarray(tensor), name=name))


def broadcast(tensor, root_rank: int = 0, *, name: Optional[str] = None):
    if root_rank < 0 or root_rank >= basics.size():
        raise ValueError(
            f"root_rank {root_rank} out of range for size {basics.size()}"
        )
    eng = _engine()
    if eng is None:
        return jnp.asarray(tensor)
    return jnp.asarray(eng.broadcast(np.asarray(tensor), root_rank,
                                     name=name))


def reducescatter(tensor, *, op=Sum, average=None,
                  name: Optional[str] = None):
    """Sum across processes, keep this rank's dim-0 slice (rows split as
    evenly as possible, earlier ranks take the remainder — the negotiated
    partitioning comes back via the handle's result shape)."""
    op = _resolve_op(op, average)
    eng = _engine()
    if eng is None:
        # World of one: reduce is identity (any op); keep the full shard.
        return jnp.asarray(tensor)
    if op not in _WIRE_OPS:
        raise NotImplementedError(
            f"eager cross-process reducescatter supports "
            f"SUM/AVERAGE/MIN/MAX/PRODUCT, got {op}"
        )
    host = np.ascontiguousarray(np.asarray(tensor))
    return jnp.asarray(
        eng.reducescatter(host, average=(op is Average), name=name,
                          red_op=_WIRE_OPS[op]))


def alltoall(tensor, *, name: Optional[str] = None, splits=None,
             wire_dtype: Optional[str] = None,
             priority: Optional[int] = None):
    """Exchange dim-0 blocks between processes: output block i holds the
    block rank i addressed to this rank.  ``splits=None`` exchanges
    equal blocks (dim 0 must divide by ``size()``; mismatches surface as
    a negotiated typed error); ``splits=[n_0, .., n_{size-1}]`` sends
    ``n_d`` rows to rank d (the per-rank vectors are validated
    cross-rank into one committed size matrix, like the allgather
    geometry).  ``wire_dtype``/``priority`` ride the same seams as the
    reduction collectives (fp32 payloads only / the banded scheduler)."""
    eng = _engine()
    if eng is None:
        return jnp.asarray(tensor)
    return jnp.asarray(eng.alltoall(np.asarray(tensor), name=name,
                                    splits=splits, wire_dtype=wire_dtype,
                                    priority=priority))
