"""Eager (host-driven) collectives across processes.

Reference parity: the enqueue → negotiate → execute pipeline
(``EnqueueTensorAllreduce`` → ``RunLoopOnce`` → ``PerformOperation``,
``horovod/common/operations.cc:2029-2145, 1694-1907, 714-1362``).

This module is the Python face of that pipeline.  At ``size() == 1`` the
collectives are arithmetic identities (matching the reference under
``mpirun -np 1``), with averaging/compression semantics still applied so
code paths are identical at any scale.  At ``size() > 1`` calls are routed
through the native negotiation engine (``horovod_tpu.cpp``) which establishes
a globally agreed, identically ordered, fused batch of collectives per cycle
— the reference's central correctness idea — and then executes them either
over the global device mesh (XLA data plane) or the host socket data plane.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax.numpy as jnp

from horovod_tpu.common.basics import basics
from horovod_tpu.ops.collective_ops import Average, ReduceOp, Sum
from horovod_tpu.ops.compression import Compression

__all__ = ["allreduce", "grouped_allreduce", "allgather", "broadcast"]


def _resolve_op(op, average):
    if average is not None:
        return Average if average else Sum
    return op


def _engine():
    """The multi-process negotiation engine (None at size 1)."""
    if basics.size() == 1:
        return None
    try:
        from horovod_tpu.runtime import engine
    except ImportError as e:
        raise NotImplementedError(
            "eager collectives at size > 1 require the negotiation engine "
            "(horovod_tpu.runtime.engine), which is not available: "
            f"{e}"
        ) from e
    return engine.get_engine()


def allreduce(tensor, *, op=Average, average=None,
              compression=Compression.none, name: Optional[str] = None):
    op = _resolve_op(op, average)
    eng = _engine()
    if eng is None:
        wire, ctx = compression.compress(jnp.asarray(tensor))
        return compression.decompress(wire, ctx)
    return eng.allreduce(tensor, op=op, compression=compression, name=name)


def grouped_allreduce(tensors: Sequence, *, op=Average, average=None,
                      compression=Compression.none,
                      name: Optional[str] = None):
    return [
        allreduce(t, op=op, average=average, compression=compression,
                  name=None if name is None else f"{name}.{i}")
        for i, t in enumerate(tensors)
    ]


def allgather(tensor, *, name: Optional[str] = None):
    eng = _engine()
    if eng is None:
        return jnp.asarray(tensor)
    return eng.allgather(tensor, name=name)


def broadcast(tensor, root_rank: int = 0, *, name: Optional[str] = None):
    eng = _engine()
    if eng is None:
        if root_rank != 0:
            raise ValueError(
                f"root_rank {root_rank} out of range for size 1"
            )
        return jnp.asarray(tensor)
    return eng.broadcast(tensor, root_rank=root_rank, name=name)
