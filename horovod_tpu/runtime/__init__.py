"""Host-side runtime: eager collectives, negotiation engine bridge."""


def engine_or_none():
    """The native multi-process engine, or None at size 1 (every caller's
    size-1 fast path).  Lives here, jax-free, so the torch/tf frontends
    can share it without pulling jax into their worker processes."""
    from horovod_tpu.common.basics import basics

    if basics.size() == 1:
        return None
    from horovod_tpu.runtime.engine import get_engine

    return get_engine()
