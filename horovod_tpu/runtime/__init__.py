"""Host-side runtime: eager collectives, negotiation engine bridge."""
