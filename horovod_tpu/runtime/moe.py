"""Expert-parallel MoE training plane (Shazeer et al. sparsely-gated
MoE; Lepikhin et al. GShard — dispatch/combine over alltoall).

The FSDP plane (`runtime/fsdp.py`) shards PARAMETERS that every rank
uses; this plane shards EXPERTS — disjoint parameter sets that each see
only the tokens routed to them.  Tokens move, parameters stay:

* **gating**: a replicated router projects each token to expert logits;
  deterministic top-k selection (stable argsort — ties break toward the
  lower expert id on every rank) with full-softmax gate weights;
* **dispatch**: every kept (token, slot) assignment is a payload row
  ``[features.., expert_id]`` routed to the expert's owner rank via ONE
  variable-split :meth:`Engine.alltoall` (splits = per-destination row
  counts, negotiated cross-rank by the engine's committed size matrix),
  named ``moe.dispatch*`` so the timeline marks it ``MOE_DISPATCH`` and
  enqueued at priority band 0 — routing traffic preempts bulk gradient
  bands under HOROVOD_PRIORITY_BANDS;
* **capacity**: each expert processes at most ``capacity =
  ceil(cf * topk * total_tokens / n_experts)`` rows, first-come in
  GLOBAL token order (ranks send their contiguous batch shard in token
  order, and the engine lays alltoall output out in source-rank order,
  so arrival order IS global token order).  Overflow rows return zero
  features and are counted into the engine's ``moe_tokens_dropped``
  telemetry counter via :func:`note_moe_dispatch` — the drop count is
  deterministic and world-size invariant;
* **combine**: expert outputs ride the return alltoall with the
  TRANSPOSED splits (this rank's recv counts — the committed matrix
  column, obtained from an equal-split int64 counts exchange), then
  each token accumulates ``gate * expert_out`` in slot order.

Bit-exactness anchor (the tests' contract): a step at ANY world size is
bit-identical to the single-rank dense-gated reference
(``MoeLayer(..., world=(0, 1))``) on the same global batch, because

* expert math is row-at-a-time (``_expert_rows``) — a row's bytes never
  depend on its batch neighbours or arrival position;
* drop decisions replay in global token order (above);
* the router gradient is computed from ALLGATHERED inputs/dlogits, so
  every rank runs the exact same two matmuls the reference runs (no
  ring-association drift from allreducing partial sums);
* at size 1 the engine alltoall is a pure identity memcpy (no wire, no
  codec), collapsing the distributed path onto the reference path.

Deliberately jax/torch-free (numpy + the native engine), like
runtime.fsdp — both frontends drive this plane, and
``DistributedOptimizer`` composes by treating ``router_params()`` as
replicated (reduce their grads) and ``expert_params()`` as rank-local
(NEVER reduce them — each rank owns a disjoint expert set).
"""

from __future__ import annotations

import math
import os
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from horovod_tpu.runtime import engine_or_none
from horovod_tpu.runtime.engine import note_moe_dispatch

__all__ = ["MoeLayer", "moe_experts_default", "moe_capacity_factor_default",
           "moe_topk_default", "moe_capacity", "moe_stats",
           "reset_moe_stats"]


def moe_experts_default(world_size: int = 1) -> int:
    """``HOROVOD_MOE_EXPERTS`` (lenient-parsed): global expert count.
    Defaults to the world size (one expert per rank); clamped up to the
    world size so every rank owns at least one expert."""
    raw = os.environ.get("HOROVOD_MOE_EXPERTS", "")
    try:
        n = int(raw) if raw.strip() else int(world_size)
    except ValueError:
        n = int(world_size)
    return max(int(world_size), n)


def moe_capacity_factor_default() -> float:
    """``HOROVOD_MOE_CAPACITY_FACTOR`` (lenient-parsed): slack on the
    perfect-balance per-expert token budget.  Default 1.25 (the GShard
    training setting); floor 0.0 means capacity 0 — every token drops
    (the drop-accounting soak's degenerate arm)."""
    raw = os.environ.get("HOROVOD_MOE_CAPACITY_FACTOR", "")
    try:
        return max(0.0, float(raw)) if raw.strip() else 1.25
    except ValueError:
        return 1.25


def moe_topk_default() -> int:
    """``HOROVOD_MOE_TOPK`` (lenient-parsed): experts per token.
    Default 2 (GShard top-2 gating); floor 1."""
    raw = os.environ.get("HOROVOD_MOE_TOPK", "")
    try:
        return max(1, int(raw)) if raw.strip() else 2
    except ValueError:
        return 2


def moe_capacity(total_tokens: int, n_experts: int, topk: int,
                 capacity_factor: float) -> int:
    """The per-expert row budget: ``ceil(cf * topk * tokens / experts)``
    — a pure function of committed step geometry, so every rank (and
    the single-rank reference) agrees without negotiation."""
    return int(math.ceil(capacity_factor * topk * total_tokens
                         / max(1, n_experts)))


# -- the plane's stats() slice (Python-side, like the FSDP plane's:
#    dispatch bookkeeping lives above the engine; the authoritative
#    moe_tokens_dropped counter lives IN the engine so it survives this
#    module's reset and rides TELEM frames).  capacity_factor and
#    experts are gauges (current config), dispatches is cumulative. --

_STATS_LOCK = threading.Lock()
_DISPATCHES = 0
_CAPACITY_FACTOR = 0.0
_EXPERTS = 0


def moe_stats() -> dict:
    with _STATS_LOCK:
        return {
            "moe_dispatches": _DISPATCHES,
            "moe_capacity_factor": _CAPACITY_FACTOR,
            "moe_experts": _EXPERTS,
        }


def reset_moe_stats() -> None:
    """Zero the plane gauges/counters (tests; the engine-side
    ``moe_tokens_dropped`` counter is process-lifetime, like every
    TELEM counter)."""
    global _DISPATCHES, _CAPACITY_FACTOR, _EXPERTS
    with _STATS_LOCK:
        _DISPATCHES = 0
        _CAPACITY_FACTOR = 0.0
        _EXPERTS = 0


def _note_dispatch(capacity_factor: float, experts: int) -> None:
    global _DISPATCHES, _CAPACITY_FACTOR, _EXPERTS
    with _STATS_LOCK:
        _DISPATCHES += 1
        _CAPACITY_FACTOR = float(capacity_factor)
        _EXPERTS = int(experts)


def _expert_rows(rows: np.ndarray, w1: np.ndarray, b1: np.ndarray,
                 w2: np.ndarray, b2: np.ndarray) -> np.ndarray:
    """Two-layer relu MLP applied ROW AT A TIME.  One row in, one row
    out, independent of batch shape — the property that makes a token's
    bytes identical whether it was computed on its owner rank among N
    neighbours or in the single-rank reference among T."""
    out = np.empty((rows.shape[0], w2.shape[1]), dtype=np.float32)
    for i in range(rows.shape[0]):
        h = np.maximum(rows[i] @ w1 + b1, np.float32(0))
        out[i] = h @ w2 + b2
    return out


def _rows_dot(rows: np.ndarray, w: np.ndarray) -> np.ndarray:
    """``rows @ w`` computed ROW AT A TIME — same rationale as
    :func:`_expert_rows`: a batched gemm's per-row bytes can shift with
    the batch extent, and the batch extent differs between a rank's
    shard and the single-rank reference."""
    out = np.empty((rows.shape[0], w.shape[1]), dtype=np.float32)
    for i in range(rows.shape[0]):
        out[i] = rows[i] @ w
    return out


class MoeLayer:
    """One expert-parallel MoE layer: replicated top-k router + a
    disjoint contiguous block of two-layer MLP experts per rank.

    Every rank constructs the layer with the same arguments; parameter
    init draws ALL experts from one seeded stream and keeps the owned
    block, so the union across ranks is bit-identical to the reference
    layer's full set.  ``world=(0, 1)`` builds the single-rank
    dense-gated reference (all experts local, no engine) — the
    bit-exactness anchor.

    >>> layer = MoeLayer(d_model=16, d_hidden=32)
    >>> y, cache = layer.forward(x_shard)          # x: [T_local, d]
    >>> dx = layer.backward(dy_shard, cache)       # accumulates grads
    >>> layer.apply_grads(lr=0.1)                  # SGD, zeroes grads
    """

    #: Per-process construction counter — two layers in one process get
    #: distinct collective names (same contract as FlatSharder).
    _instances = 0

    def __init__(self, d_model: int, d_hidden: Optional[int] = None, *,
                 n_experts: Optional[int] = None, topk: Optional[int] = None,
                 capacity_factor: Optional[float] = None, seed: int = 0,
                 name: str = "moe", wire_dtype: Optional[str] = None,
                 world: Optional[Tuple[int, int]] = None):
        if world is None:
            from horovod_tpu.common.basics import basics
            if basics.is_initialized():
                world = (basics.rank(), basics.size())
            else:
                world = (0, 1)
        self.rank, self.size = int(world[0]), int(world[1])
        self.d_model = int(d_model)
        self.d_hidden = int(d_hidden) if d_hidden is not None \
            else 2 * self.d_model
        self.n_experts = (moe_experts_default(self.size)
                          if n_experts is None else int(n_experts))
        if self.n_experts % self.size != 0:
            raise ValueError(
                f"n_experts {self.n_experts} must be divisible by the "
                f"world size {self.size} (contiguous expert blocks per "
                "rank)")
        self.experts_per_rank = self.n_experts // self.size
        self.topk = moe_topk_default() if topk is None else max(1, int(topk))
        if self.topk > self.n_experts:
            raise ValueError(f"topk {self.topk} > n_experts "
                             f"{self.n_experts}")
        self.capacity_factor = (moe_capacity_factor_default()
                                if capacity_factor is None
                                else max(0.0, float(capacity_factor)))
        self.wire_dtype = wire_dtype
        self.name = f"{name}.{MoeLayer._instances}"
        MoeLayer._instances += 1
        self._dispatches = 0

        # Every rank draws the SAME full parameter set (one seeded
        # stream) and keeps its contiguous expert block — the union
        # across ranks is bitwise the reference's full set.
        rng = np.random.RandomState(seed)
        scale1 = np.float32(1.0 / math.sqrt(self.d_model))
        scale2 = np.float32(1.0 / math.sqrt(self.d_hidden))
        self.wg = (rng.standard_normal((self.d_model, self.n_experts))
                   .astype(np.float32) * scale1)
        full_w1 = (rng.standard_normal(
            (self.n_experts, self.d_model, self.d_hidden))
            .astype(np.float32) * scale1)
        full_w2 = (rng.standard_normal(
            (self.n_experts, self.d_hidden, self.d_model))
            .astype(np.float32) * scale2)
        lo = self.rank * self.experts_per_rank
        hi = lo + self.experts_per_rank
        self.expert_lo = lo
        self.w1 = full_w1[lo:hi].copy()
        self.w2 = full_w2[lo:hi].copy()
        self.b1 = np.zeros((self.experts_per_rank, self.d_hidden),
                           dtype=np.float32)
        self.b2 = np.zeros((self.experts_per_rank, self.d_model),
                           dtype=np.float32)
        self.zero_grads()

    # -- parameter views for DistributedOptimizer composition --

    def router_params(self) -> List[np.ndarray]:
        """Replicated parameters — reduce their grads across ranks."""
        return [self.wg]

    def expert_params(self) -> List[np.ndarray]:
        """Rank-LOCAL parameters (this rank's expert block) — never
        reduce their grads; every rank owns a disjoint set."""
        return [self.w1, self.b1, self.w2, self.b2]

    def zero_grads(self) -> None:
        self.g_wg = np.zeros_like(self.wg)
        self.g_w1 = np.zeros_like(self.w1)
        self.g_b1 = np.zeros_like(self.b1)
        self.g_w2 = np.zeros_like(self.w2)
        self.g_b2 = np.zeros_like(self.b2)

    def owner(self, expert: int) -> int:
        """The rank owning ``expert`` (contiguous blocks)."""
        return int(expert) // self.experts_per_rank

    # -- wire helpers --

    def _alltoall(self, payload: np.ndarray, splits: List[int],
                  tag: str) -> np.ndarray:
        """One engine alltoall (band 0, named ``moe.*`` for the
        MOE_DISPATCH timeline span); identity at world size 1."""
        eng = engine_or_none() if self.size > 1 else None
        if eng is None:
            if self.size > 1:
                raise RuntimeError(
                    "MoeLayer built for a multi-rank world but no engine "
                    "is running")
            return payload.copy()
        return np.asarray(eng.alltoall(
            payload, name=f"moe.{self.name}.{tag}.{self._dispatches}",
            splits=splits, wire_dtype=self.wire_dtype, priority=0))

    def _exchange_counts(self, counts: List[int], tag: str) -> List[int]:
        """The transposed-splits negotiation: an equal-split int64
        alltoall of each rank's send-count vector returns this rank's
        COLUMN of the committed size matrix — the splits of the return
        alltoall."""
        if self.size == 1:
            return list(counts)
        eng = engine_or_none()
        if eng is None:
            raise RuntimeError(
                "MoeLayer built for a multi-rank world but no engine "
                "is running")
        cnt = np.asarray(counts, dtype=np.int64).reshape(self.size, 1)
        col = np.asarray(eng.alltoall(
            cnt, name=f"moe.{self.name}.{tag}.counts.{self._dispatches}",
            priority=0))
        return [int(v) for v in col.reshape(-1)]

    # -- the gate --

    def gate(self, x: np.ndarray) -> Tuple[np.ndarray, np.ndarray,
                                           np.ndarray]:
        """Deterministic top-k gating: full softmax over expert logits,
        stable-argsort top-k (ties toward the lower expert id), gate
        weight = the softmax probability of the chosen expert (Switch /
        GShard style, no renormalisation — keeps the vjp exact).
        Returns ``(probs [T,E], topk_idx [T,k], gates [T,k])``."""
        logits = _rows_dot(x, self.wg)
        m = logits.max(axis=1, keepdims=True)
        ex = np.exp(logits - m)
        probs = (ex / ex.sum(axis=1, keepdims=True)).astype(np.float32)
        topk_idx = np.argsort(-probs, axis=1, kind="stable")[:, :self.topk]
        gates = np.take_along_axis(probs, topk_idx, axis=1)
        return probs, topk_idx, gates

    # -- forward --

    def forward(self, x: np.ndarray) -> Tuple[np.ndarray, dict]:
        """One MoE forward over this rank's contiguous batch shard
        ``x [T_local, d_model]``.  Returns ``(y, cache)`` where ``y`` is
        the gate-combined expert mixture and ``cache`` feeds
        :meth:`backward`.  Dispatch and combine each ride one
        variable-split alltoall; dropped (over-capacity) assignments
        contribute zero and are counted into ``moe_tokens_dropped``."""
        x = np.ascontiguousarray(x, dtype=np.float32)
        if x.ndim != 2 or x.shape[1] != self.d_model:
            raise ValueError(
                f"expected x [tokens, {self.d_model}], got {x.shape}")
        t_local = x.shape[0]
        probs, topk_idx, gates = self.gate(x)

        # Assignments ordered (dest rank, token, slot): each dest block
        # is in local token order, so concatenated across ranks the
        # expert sees GLOBAL token order (contiguous batch shards).
        by_dest: List[List[Tuple[int, int]]] = \
            [[] for _ in range(self.size)]
        for t in range(t_local):
            for k in range(self.topk):
                e = int(topk_idx[t, k])
                by_dest[self.owner(e)].append((t, k))
        counts = [len(b) for b in by_dest]
        order = [tk for b in by_dest for tk in b]

        payload = np.empty((len(order), self.d_model + 1),
                           dtype=np.float32)
        for i, (t, k) in enumerate(order):
            payload[i, :self.d_model] = x[t]
            payload[i, self.d_model] = np.float32(topk_idx[t, k])

        recv_counts = self._exchange_counts(counts, "fwd")
        recv = self._alltoall(payload, counts, "dispatch")

        # Expert side: capacity in arrival (= global token) order.
        capacity = moe_capacity(t_local * self.size, self.n_experts,
                                self.topk, self.capacity_factor)
        used = np.zeros(self.experts_per_rank, dtype=np.int64)
        kept = np.zeros(recv.shape[0], dtype=bool)
        local_e = np.empty(recv.shape[0], dtype=np.int64)
        dropped = 0
        for i in range(recv.shape[0]):
            le = int(recv[i, self.d_model]) - self.expert_lo
            local_e[i] = le
            if used[le] < capacity:
                used[le] += 1
                kept[i] = True
            else:
                dropped += 1
        out = np.zeros((recv.shape[0], self.d_model), dtype=np.float32)
        for le in range(self.experts_per_rank):
            sel = np.nonzero(kept & (local_e == le))[0]
            if sel.size:
                out[sel] = _expert_rows(recv[sel, :self.d_model],
                                        self.w1[le], self.b1[le],
                                        self.w2[le], self.b2[le])

        note_moe_dispatch(dropped)
        self._dispatches += 1
        _note_dispatch(self.capacity_factor, self.n_experts)

        back = self._alltoall(out, recv_counts, "combine")

        # Combine: slot-ordered accumulation of gate * expert_out.
        expert_out = np.zeros((t_local, self.topk, self.d_model),
                              dtype=np.float32)
        for i, (t, k) in enumerate(order):
            expert_out[t, k] = back[i]
        y = np.zeros((t_local, self.d_model), dtype=np.float32)
        for k in range(self.topk):
            y += gates[:, k:k + 1] * expert_out[:, :, :][:, k]
        cache = {"x": x, "probs": probs, "topk_idx": topk_idx,
                 "gates": gates, "order": order, "counts": counts,
                 "recv_counts": recv_counts, "recv": recv, "kept": kept,
                 "local_e": local_e, "expert_out": expert_out,
                 "dropped": dropped}
        return y, cache

    # -- backward --

    def backward(self, dy: np.ndarray, cache: dict) -> np.ndarray:
        """Manual vjp of :meth:`forward`: accumulates expert grads
        (rank-local) and the router grad (computed from ALLGATHERED
        inputs and dlogits, so every rank runs the reference's exact
        matmul — the router-grad half of the bit-exactness anchor) and
        returns ``dx [T_local, d_model]``."""
        dy = np.ascontiguousarray(dy, dtype=np.float32)
        x, order = cache["x"], cache["order"]
        gates, topk_idx = cache["gates"], cache["topk_idx"]
        probs, expert_out = cache["probs"], cache["expert_out"]
        t_local = x.shape[0]

        # Upstream into each expert output row: gate * dy[token].
        d_out = np.empty((len(order), self.d_model), dtype=np.float32)
        for i, (t, k) in enumerate(order):
            d_out[i] = gates[t, k] * dy[t]

        # Ship expert-output grads along the forward routing (same
        # splits), backprop rows on the owner, ship dx rows back.
        recv_d = self._alltoall(d_out, cache["counts"], "bwd.dispatch")
        recv, kept, local_e = cache["recv"], cache["kept"], cache["local_e"]
        dx_rows = np.zeros((recv.shape[0], self.d_model), dtype=np.float32)
        for i in range(recv.shape[0]):
            if not kept[i]:
                continue
            le = int(local_e[i])
            xi = recv[i, :self.d_model]
            h_pre = xi @ self.w1[le] + self.b1[le]
            h = np.maximum(h_pre, np.float32(0))
            g = recv_d[i]
            self.g_w2[le] += np.outer(h, g)
            self.g_b2[le] += g
            dh = g @ self.w2[le].T
            dh = np.where(h_pre > 0, dh, np.float32(0))
            self.g_w1[le] += np.outer(xi, dh)
            self.g_b1[le] += dh
            dx_rows[i] = dh @ self.w1[le].T
        back = self._alltoall(dx_rows, cache["recv_counts"], "bwd.combine")

        dx = np.zeros((t_local, self.d_model), dtype=np.float32)
        d_gates = np.zeros_like(gates)
        for i, (t, k) in enumerate(order):
            dx[t] += back[i]
            d_gates[t, k] = np.float32(np.dot(dy[t], expert_out[t, k]))

        # Router vjp through the full softmax: dP is sparse on the
        # selected entries; dlogits = P * (dP - sum(dP * P)).
        d_probs = np.zeros_like(probs)
        np.put_along_axis(d_probs, topk_idx, d_gates, axis=1)
        inner = (d_probs * probs).sum(axis=1, keepdims=True)
        dlogits = (probs * (d_probs - inner)).astype(np.float32)
        dx += _rows_dot(dlogits, np.ascontiguousarray(self.wg.T))

        # The anchor: allgather (x, dlogits) so EVERY rank computes the
        # router grad with the reference's one matmul over the global
        # batch — bitwise identical at every world size.
        eng = engine_or_none() if self.size > 1 else None
        if eng is None:
            x_full, dl_full = x, dlogits
        else:
            x_full = np.asarray(eng.allgather(
                x, name=f"moe.{self.name}.router.agx.{self._dispatches}"))
            dl_full = np.asarray(eng.allgather(
                dlogits,
                name=f"moe.{self.name}.router.agdl.{self._dispatches}"))
        self.g_wg += x_full.T @ dl_full
        return dx

    def apply_grads(self, lr: float) -> None:
        """Plain SGD on router + owned experts, then zero grads.  The
        router grad is already the GLOBAL-batch grad (backward's
        allgather), so no reduction happens here — every rank applies
        the same bytes and the replicas stay bit-identical."""
        lr = np.float32(lr)
        self.wg -= lr * self.g_wg
        self.w1 -= lr * self.g_w1
        self.b1 -= lr * self.g_b1
        self.w2 -= lr * self.g_w2
        self.b2 -= lr * self.g_b2
        self.zero_grads()
