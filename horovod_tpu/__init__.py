"""horovod_tpu: a TPU-native distributed training framework.

A ground-up rebuild of Horovod 0.15.1's capabilities (reference:
``/root/reference``) designed for TPUs: SPMD over ``jax.sharding.Mesh``
device meshes, XLA collectives on ICI/DCN instead of NCCL/MPI, trace-time
tensor fusion instead of staging buffers, and a native C++ coordinator for
the host-driven (eager / PyTorch) path.

Frontends (mirroring ``horovod.tensorflow`` / ``horovod.torch`` /
``horovod.keras``):

* ``horovod_tpu.jax`` — flagship, for JAX/flax/optax training.

(``horovod_tpu.torch`` and ``horovod_tpu.keras`` frontends are planned; see
SURVEY.md §7 steps 5-6.)
"""

from horovod_tpu import elastic
from horovod_tpu.common import (
    epoch,
    fleet_stats,
    init,
    is_initialized,
    local_rank,
    local_size,
    mpi_threads_supported,
    rank,
    shutdown,
    size,
)
from horovod_tpu.version import __version__

__all__ = [
    "__version__",
    "elastic",
    "init",
    "shutdown",
    "is_initialized",
    "rank",
    "size",
    "local_rank",
    "local_size",
    "epoch",
    "fleet_stats",
    "mpi_threads_supported",
]
