"""torch ↔ JAX tensor bridge.

SURVEY.md §7 "hard parts" names PyTorch-on-TPU: with no CUDA in a TPU pod,
the torch frontend must hand tensors between torch (host CPU) and JAX (the
accelerator path).  The reference's precedent is the ``CudaOnCPU`` staging
pattern (reference torch/mpi_ops_v2.cc:78-110: GPU tensors staged through
CPU copies); here the handoff is dlpack — zero-copy on CPU, one
host↔device transfer to/from the TPU:

    x_jax = bridge.to_jax(torch_tensor)        # CPU: zero-copy
    y = jax.jit(model)(x_jax)                  # TPU compute
    torch_out = bridge.from_jax(y)             # device->host + zero-copy

Falls back to a numpy copy for dtypes/layouts dlpack refuses (bool,
non-contiguous), so the bridge never fails where a copy would work.
"""

from __future__ import annotations

import numpy as np
import torch

__all__ = ["to_jax", "from_jax"]


def to_jax(tensor: torch.Tensor, device=None):
    """A JAX array viewing (CPU, zero-copy when possible) or holding a copy
    of ``tensor``.  ``device`` optionally places the result (e.g.
    ``jax.devices()[0]`` for the TPU)."""
    import jax

    t = tensor.detach()
    try:
        arr = jax.dlpack.from_dlpack(t.contiguous())
    except Exception:
        arr = jax.numpy.asarray(t.cpu().numpy())
    if device is not None:
        arr = jax.device_put(arr, device)
    return arr


def from_jax(array) -> torch.Tensor:
    """A torch CPU tensor viewing (zero-copy when possible) or holding a
    copy of ``array``; device arrays are fetched to host first."""
    import jax

    arr = jax.device_get(array)
    try:
        return torch.from_dlpack(arr)
    except Exception:
        return torch.from_numpy(np.asarray(arr).copy())
