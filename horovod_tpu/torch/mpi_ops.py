"""Torch collective ops: sync/async/in-place variants + autograd.

Reference parity: ``horovod/torch/mpi_ops.py`` (438 LoC) — the public
``allreduce[_async][_]`` / ``allgather[_async]`` / ``broadcast[_async][_]``
surface, ``poll``/``synchronize`` handle management, and the autograd
Functions whose backward passes are themselves collectives
(mpi_ops.py:110-121, 236-254, 318-332).

TPU-native design: there is no custom torch C++ extension — torch CPU
tensors share memory with numpy views, so the native engine
(``horovod_tpu/cpp``) reduces them directly, zero-copy, in place.  Handles
are the engine's int64 handles (reference handle_manager parity).  At
``size()==1`` everything degrades to arithmetic identity with the same
handle-based API, matching the reference under ``mpirun -np 1``.
"""

from __future__ import annotations

import threading
from typing import Optional

import numpy as np
import torch

from horovod_tpu.common.basics import basics
from horovod_tpu.runtime import engine_or_none as _engine

__all__ = [
    "allreduce", "allreduce_async", "allreduce_", "allreduce_async_",
    "grouped_allreduce", "grouped_allreduce_async",
    "allgather", "allgather_async",
    "broadcast", "broadcast_async", "broadcast_", "broadcast_async_",
    "reducescatter", "reducescatter_async",
    "alltoall", "alltoall_async",
    "poll", "synchronize", "rank", "size", "local_rank", "local_size",
    "init", "shutdown",
]

init = basics.init
shutdown = basics.shutdown
rank = basics.rank
size = basics.size
local_rank = basics.local_rank
local_size = basics.local_size


def _np_view(t: torch.Tensor) -> np.ndarray:
    """Zero-copy numpy view of a contiguous CPU tensor (bf16 via ml_dtypes
    reinterpretation — numpy has no native bfloat16)."""
    if t.device.type != "cpu":
        raise ValueError(
            "horovod_tpu.torch operates on CPU tensors (accelerator work "
            "belongs to the JAX/XLA path); got device " + str(t.device)
        )
    t = t.detach()
    if not t.is_contiguous():
        raise ValueError("tensor must be contiguous for in-place collectives")
    if t.dtype == torch.bfloat16:
        import ml_dtypes

        return t.view(torch.uint16).numpy().view(ml_dtypes.bfloat16)
    return t.numpy()


# handle -> (postprocess(output_np) -> torch.Tensor)
_handle_lock = threading.Lock()
_handle_map: dict[int, tuple] = {}
# Fake handles for the size==1 fast path (negative, engine handles are >= 0).
_local_results: dict[int, torch.Tensor] = {}
_next_local = [-1]


def _register(handle: int, tensor: torch.Tensor, postprocess) -> int:
    with _handle_lock:
        _handle_map[handle] = (tensor, postprocess)
    return handle


def _local_handle(result: torch.Tensor) -> int:
    with _handle_lock:
        h = _next_local[0]
        _next_local[0] -= 1
        _local_results[h] = result
    return h


def poll(handle: int) -> bool:
    """True if the collective referenced by ``handle`` has completed
    (reference mpi_ops.py:406-421)."""
    if handle < 0:
        return True
    return _engine().poll(handle)


def synchronize(handle: int) -> torch.Tensor:
    """Wait for the collective and return its result tensor
    (reference mpi_ops.py:422-438)."""
    if handle < 0:
        with _handle_lock:
            return _local_results.pop(handle)
    eng = _engine()
    info: dict = {}
    try:
        out_np = eng.synchronize(handle, info)
    finally:
        # Release the kept-alive tensors even when the collective errored,
        # or the map entry leaks for the process lifetime.
        with _handle_lock:
            tensor, postprocess = _handle_map.pop(handle)
    return postprocess(tensor, out_np, info)


# ---------------------------------------------------------------------------
# allreduce
# ---------------------------------------------------------------------------

def _div_in_place(t: torch.Tensor, n: int) -> torch.Tensor:
    if t.is_floating_point():
        t.div_(n)
    else:
        t.floor_divide_(n)
    return t


def allreduce_async_(tensor: torch.Tensor, average: bool = True,
                     name: Optional[str] = None,
                     wire_dtype: Optional[str] = None,
                     priority: Optional[int] = None,
                     wire_advisory: bool = False) -> int:
    """In-place async sum/average over all processes.  ``wire_dtype``
    (fp32/fp16/bf16/int8/fp8) overrides the engine's HOROVOD_WIRE_DTYPE
    wire format for this tensor (fp32 payloads only;
    ``wire_advisory=True`` lets the coordinator commit the first value
    on a cross-rank disagreement — the gradient-statistics wire policy's
    contract).  ``priority`` (0 = most urgent) is the scheduling
    priority the priority-banded coordinator (HOROVOD_PRIORITY_BANDS)
    orders responses by — the DistributedOptimizer stamps it from
    parameter registration order."""
    eng = _engine()
    if eng is None:
        return _local_handle(tensor)  # sum over 1 rank = identity
    view = _np_view(tensor)
    handle = eng.enqueue_allreduce(view, name, wire_dtype=wire_dtype,
                                   priority=priority,
                                   wire_advisory=wire_advisory)

    def post(t, _out, info=None):
        if not average:
            return t
        # Divisor-correct averaging: a backup-worker partial commit
        # reduced participants < size contributions.
        n = (info or {}).get("participants") or basics.size()
        return _div_in_place(t, n)

    return _register(handle, tensor, post)


def allreduce_async(tensor: torch.Tensor, average: bool = True,
                    name: Optional[str] = None) -> int:
    out = tensor.detach().clone().contiguous()
    return allreduce_async_(out, average, name)


def _probe_allreduce_async_(tensor: torch.Tensor,
                            name: Optional[str] = None) -> int:
    """In-place layout-probe allreduce (always averaged) of placeholder
    zeros for a param whose gradient never materialized this step.
    ``synchronize`` on the returned handle raises
    :class:`horovod_tpu.runtime.engine.SparseGradRetry` if peers turn out
    to be gathering this tensor sparsely."""
    if name is None:
        # A probe exists to rendezvous with PEERS' collectives for the
        # same tensor; an invented fallback name could never match them.
        raise ValueError("layout-probe allreduce requires the tensor name")
    eng = _engine()
    if eng is None:
        return _local_handle(tensor)
    view = _np_view(tensor)
    handle = eng.enqueue_probe(view, name)

    def post(t, _out, info=None):
        n = (info or {}).get("participants") or basics.size()
        return _div_in_place(t, n)

    return _register(handle, tensor, post)


def allreduce_(tensor, average: bool = True,
               name: Optional[str] = None) -> torch.Tensor:
    return synchronize(allreduce_async_(tensor, average, name))


class _HorovodAllreduce(torch.autograd.Function):
    """Differentiable allreduce: grad of a sum-allreduce is an allreduce
    (reference mpi_ops.py:110-121)."""

    @staticmethod
    def forward(ctx, tensor, average, name):
        ctx.average = average
        return allreduce_(tensor.clone(), average, name)

    @staticmethod
    def backward(ctx, grad_output):
        return allreduce_(grad_output.contiguous().clone(),
                          ctx.average), None, None


def allreduce(tensor: torch.Tensor, average: bool = True,
              name: Optional[str] = None,
              compression=None) -> torch.Tensor:
    """Out-of-place allreduce, differentiable (reference mpi_ops.py:86-109)."""
    from horovod_tpu.torch.compression import Compression

    compression = compression or Compression.none
    wire, cctx = compression.compress(tensor)
    reduced = _HorovodAllreduce.apply(wire, average, name)
    return compression.decompress(reduced, cctx)


def grouped_allreduce_async(tensors, average: bool = True,
                            name: Optional[str] = None) -> list:
    """Allreduce many tensors in one burst: enqueued together, the
    coordinator negotiates them in the same cycle and fuses same-dtype
    batches into single ring collectives (the engine-side analogue of the
    reference's fusion buffer).  Returns one handle per tensor."""
    return [
        allreduce_async(t, average,
                        None if name is None else f"{name}.{i}")
        for i, t in enumerate(tensors)
    ]


def grouped_allreduce(tensors, average: bool = True,
                      name: Optional[str] = None) -> list:
    return [synchronize(h)
            for h in grouped_allreduce_async(tensors, average, name)]


# ---------------------------------------------------------------------------
# allgather
# ---------------------------------------------------------------------------

def allgather_async(tensor: torch.Tensor,
                    name: Optional[str] = None) -> int:
    eng = _engine()
    src = tensor.detach().contiguous()
    if src.dim() == 0:
        src = src.reshape(1)
    if eng is None:
        return _local_handle(src.clone())
    view = _np_view(src)
    handle = eng.enqueue_allgather(view, name)

    def post(_t, out_np, _info=None):
        return _from_np(out_np, tensor.dtype)

    # Keep src alive until synchronize (its memory feeds the engine).
    return _register(handle, src, post)


def _from_np(out_np: np.ndarray, dtype: torch.dtype) -> torch.Tensor:
    if dtype == torch.bfloat16:
        return torch.from_numpy(
            out_np.view(np.uint16).copy()).view(torch.bfloat16)
    return torch.from_numpy(out_np.copy())


class _HorovodAllgather(torch.autograd.Function):
    """Backward: sum-allreduce the full grad, keep own slice at the TRUE
    offset — per-rank dim-0 sizes are themselves allgathered, so ragged
    gathers differentiate correctly (reference mpi_ops.py:236-254)."""

    @staticmethod
    def forward(ctx, tensor, name):
        ctx.dim0 = tensor.shape[0] if tensor.dim() > 0 else 1
        return synchronize(allgather_async(tensor, name))

    @staticmethod
    def backward(ctx, grad_output):
        # Enqueue the tiny sizes-gather FIRST so it shares a negotiation
        # cycle with the grad allreduce instead of serializing after it.
        h_sizes = allgather_async(torch.tensor([ctx.dim0], dtype=torch.int64))
        grad = allreduce_(grad_output.contiguous().clone(), average=False)
        sizes = synchronize(h_sizes)
        offset = int(sizes[:basics.rank()].sum().item())
        return grad.narrow(0, offset, ctx.dim0), None


def allgather(tensor: torch.Tensor,
              name: Optional[str] = None) -> torch.Tensor:
    """Concatenate each rank's tensor along dim 0; per-rank dim 0 may differ
    (negotiated at runtime).  Differentiable, including ragged dim 0."""
    return _HorovodAllgather.apply(tensor, name)


# ---------------------------------------------------------------------------
# broadcast
# ---------------------------------------------------------------------------

def broadcast_async_(tensor: torch.Tensor, root_rank: int,
                     name: Optional[str] = None) -> int:
    if root_rank < 0 or root_rank >= basics.size():
        raise ValueError(
            f"root_rank {root_rank} out of range for size {basics.size()}")
    eng = _engine()
    if eng is None:
        return _local_handle(tensor)
    view = _np_view(tensor)
    handle = eng.enqueue_broadcast(view, root_rank, name)
    return _register(handle, tensor, lambda t, _out, _info=None: t)


def broadcast_async(tensor: torch.Tensor, root_rank: int,
                    name: Optional[str] = None) -> int:
    return broadcast_async_(tensor.detach().clone().contiguous(),
                            root_rank, name)


def broadcast_(tensor, root_rank: int,
               name: Optional[str] = None) -> torch.Tensor:
    return synchronize(broadcast_async_(tensor, root_rank, name))


class _HorovodBroadcast(torch.autograd.Function):
    """Backward: allreduce grads; non-root ranks contribute then zero
    (reference mpi_ops.py:318-332)."""

    @staticmethod
    def forward(ctx, tensor, root_rank, name):
        ctx.root_rank = root_rank
        return broadcast_(tensor.clone(), root_rank, name)

    @staticmethod
    def backward(ctx, grad_output):
        grad = allreduce_(grad_output.contiguous().clone(), average=False)
        if basics.rank() != ctx.root_rank:
            grad = grad * 0
        return grad, None, None


def broadcast(tensor: torch.Tensor, root_rank: int,
              name: Optional[str] = None) -> torch.Tensor:
    return _HorovodBroadcast.apply(tensor, root_rank, name)


# ---------------------------------------------------------------------------
# reducescatter / alltoall (engine extensions beyond the reference surface)
# ---------------------------------------------------------------------------

def reducescatter_async(tensor: torch.Tensor,
                        name: Optional[str] = None) -> int:
    """Sum across ranks, keep this rank's dim-0 slice (rows split as evenly
    as possible; earlier ranks take the remainder)."""
    eng = _engine()
    src = tensor.detach().contiguous()
    if eng is None:
        return _local_handle(src.clone())
    view = _np_view(src)
    handle = eng.enqueue_reducescatter(view, name)
    return _register(
        handle, src,
        lambda _t, out_np, _info=None: _from_np(out_np, tensor.dtype))


class _HorovodReducescatter(torch.autograd.Function):
    """Backward of sum-reducescatter is allgather of the slice grads."""

    @staticmethod
    def forward(ctx, tensor, name):
        return synchronize(reducescatter_async(tensor, name))

    @staticmethod
    def backward(ctx, grad_output):
        return synchronize(allgather_async(grad_output.contiguous())), None


def reducescatter(tensor: torch.Tensor,
                  name: Optional[str] = None) -> torch.Tensor:
    return _HorovodReducescatter.apply(tensor, name)


def alltoall_async(tensor: torch.Tensor,
                   name: Optional[str] = None, *, splits=None,
                   wire_dtype: Optional[str] = None,
                   priority: Optional[int] = None) -> int:
    """Exchange dim-0 blocks: output block i came from rank i.  With
    ``splits=None`` the blocks are equal (dim 0 must be divisible by
    ``size()``); ``splits=[n_0, ..]`` sends ``n_d`` rows to rank d (the
    variable-split MoE dispatch primitive — the engine validates the
    per-rank vectors into one committed size matrix)."""
    eng = _engine()
    src = tensor.detach().contiguous()
    if eng is None:
        return _local_handle(src.clone())
    view = _np_view(src)
    handle = eng.enqueue_alltoall(view, name, splits=splits,
                                  wire_dtype=wire_dtype, priority=priority)
    return _register(
        handle, src,
        lambda _t, out_np, _info=None: _from_np(out_np, tensor.dtype))


class _HorovodAlltoall(torch.autograd.Function):
    """Alltoall is a permutation of blocks across ranks; its adjoint is the
    inverse permutation — another alltoall.  With variable splits the
    adjoint's splits are the TRANSPOSED matrix row: this rank's recv
    counts, i.e. the committed matrix column, recovered from the forward
    output (``recv_splits``)."""

    @staticmethod
    def forward(ctx, tensor, name, splits, recv_splits):
        ctx.recv_splits = recv_splits
        return synchronize(alltoall_async(tensor, name, splits=splits))

    @staticmethod
    def backward(ctx, grad_output):
        return (synchronize(alltoall_async(grad_output.contiguous(),
                                           splits=ctx.recv_splits)),
                None, None, None)


def alltoall(tensor: torch.Tensor, name: Optional[str] = None, *,
             splits=None, recv_splits=None) -> torch.Tensor:
    """Differentiable alltoall.  When ``splits`` is given, pass
    ``recv_splits`` (this rank's per-source recv counts — e.g. from an
    equal-split counts exchange, see runtime/moe.py) so the backward
    alltoall can route gradient rows back along the transposed matrix."""
    if splits is not None and recv_splits is None:
        raise ValueError(
            "variable-split alltoall needs recv_splits for its backward "
            "(this rank's recv counts: the committed matrix column)")
    return _HorovodAlltoall.apply(tensor, name, splits, recv_splits)
