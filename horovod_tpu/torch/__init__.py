"""PyTorch frontend.

Reference parity: ``horovod/torch/__init__.py`` (301 LoC) —
``DistributedOptimizer`` (gradient hooks firing async allreduces during
backward, so communication overlaps remaining compute),
``broadcast_parameters`` and ``broadcast_optimizer_state`` (including the
scalar tensor-ization dance), plus the full op surface re-exported from
``mpi_ops``.

TPU context: torch runs on host CPU here (no CUDA in a TPU pod); this
frontend gives torch training scripts the same scaling API they had with
the reference, with the native engine's ring collectives over DCN as the
data plane.  The heavy-compute path on TPU is the JAX frontend; the torch
frontend exists for capability parity and host-side workloads.
"""

from __future__ import annotations

import collections
from typing import Optional

import torch

from horovod_tpu.common.basics import basics
from horovod_tpu.torch import bridge
from horovod_tpu.torch.compression import Compression
from horovod_tpu.torch.mpi_ops import (
    allgather,
    allgather_async,
    allreduce,
    allreduce_,
    allreduce_async,
    allreduce_async_,
    alltoall,
    alltoall_async,
    broadcast,
    broadcast_,
    broadcast_async,
    broadcast_async_,
    grouped_allreduce,
    grouped_allreduce_async,
    init,
    local_rank,
    local_size,
    poll,
    rank,
    reducescatter,
    reducescatter_async,
    shutdown,
    size,
    synchronize,
)

is_initialized = basics.is_initialized
epoch = basics.epoch
mpi_threads_supported = basics.mpi_threads_supported

__all__ = [
    "init", "shutdown", "is_initialized", "rank", "size", "local_rank",
    "local_size", "epoch", "mpi_threads_supported",
    "allreduce", "allreduce_async", "allreduce_", "allreduce_async_",
    "grouped_allreduce", "grouped_allreduce_async",
    "allgather", "allgather_async",
    "broadcast", "broadcast_async", "broadcast_", "broadcast_async_",
    "reducescatter", "reducescatter_async", "alltoall", "alltoall_async",
    "poll", "synchronize", "Compression", "bridge",
    "DistributedOptimizer", "broadcast_parameters",
    "broadcast_optimizer_state",
]


class _DistributedOptimizer(torch.optim.Optimizer):
    """Mixin pattern from the reference (torch/__init__.py:31-144):
    dynamically combined with the user's optimizer class so
    ``isinstance(opt, UserOptimizer)`` stays true and checkpoints load
    without this library installed."""

    def __init__(self, params, named_parameters=None,
                 compression=Compression.none,
                 backward_passes_per_step=1,
                 sparse_as_dense=False,
                 local_sgd_steps=None):
        from horovod_tpu.elastic.state import (LocalSGD,
                                               default_local_sgd_steps)

        super(self.__class__, self).__init__(params)
        self._compression = compression
        self._bpps = backward_passes_per_step
        self._sparse_as_dense = sparse_as_dense
        # Local SGD (DiLoCo-style periodic sync): H purely-local steps,
        # then one outer allreduce of the MODEL delta in step().  H <= 1
        # keeps the per-step gradient allreduce path byte-identical.
        self._local_sgd_steps = (default_local_sgd_steps()
                                 if local_sgd_steps is None
                                 else max(1, int(local_sgd_steps)))
        # With Compression.topk the policy ships the outer MODEL delta
        # through the sparse path (its own epoch-stamped residuals).
        self._local_sgd = (LocalSGD(self._local_sgd_steps,
                                    compression=compression)
                           if self._local_sgd_steps > 1 else None)
        # Statistics-driven per-leaf wire policy (HOROVOD_WIRE_POLICY=1):
        # int8 for large embedding-shaped grads, fp32 for norm/bias
        # leaves, stamped advisory (see runtime/wire_policy.py).
        from horovod_tpu.runtime import wire_policy as _wp

        self._wire_policy = (_wp.default_policy()
                             if _wp.policy_enabled() else None)

        if named_parameters is not None:
            named_parameters = list(named_parameters)
        else:
            # Single running counter across param groups: per-group
            # numbering would hand two groups the same synthesized name,
            # and names are load-bearing for collective rendezvous.
            named_parameters = [
                (f"allreduce.noname.{i}", v)
                for i, v in enumerate(
                    v for param_group in self.param_groups
                    for v in param_group["params"])
            ]
        # Sanity checks mirroring the reference (torch/__init__.py:41-67).
        all_params = {
            id(v) for group in self.param_groups for v in group["params"]
        }
        named_ids = {id(v) for _, v in named_parameters}
        if len(named_parameters) != len(named_ids):
            raise ValueError("named_parameters contains duplicate parameters")
        unnamed = all_params - named_ids
        if unnamed and len(named_parameters) > 0 and named_ids != all_params:
            raise ValueError(
                f"named_parameters covers {len(named_ids)} parameters but "
                f"the optimizer has {len(all_params)}; provide names for all"
            )
        self._param_names = {id(v): k for k, v in named_parameters}
        # Registration order IS the scheduling priority (0 = first
        # registered ≈ front layer ≈ needed first by the next forward):
        # backward produces these gradients LAST, but the priority-
        # banded coordinator (HOROVOD_PRIORITY_BANDS) dispatches them
        # first so step N+1's forward never waits on step N's tail.
        self._param_priority = {
            id(v): i for i, (_k, v) in enumerate(named_parameters)
        }

        self._handles: dict = {}
        self._grad_accs = []
        # id(param) -> sparse_dim for params that have produced a sparse
        # gradient: the force-allreduce fallback must keep using the sparse
        # gather path for them (a dense zero allreduce would never
        # rendezvous with peers' '<name>.idx'/'.vals' allgathers and the
        # job would stall).
        self._sparse_params: dict = {}
        self._passes_left = collections.defaultdict(
            lambda: self._bpps)
        # Hooks are registered at any size so behavior (incl. the
        # force-allreduce-in-step contract) is identical at any scale.
        self._register_hooks()

    # -- hook pipeline (reference torch/__init__.py:72-96) --

    def _register_hooks(self):
        for group in self.param_groups:
            for p in group["params"]:
                if p.requires_grad:
                    self._grad_accs.append(
                        p.register_post_accumulate_grad_hook(
                            self._make_hook()))

    def _make_hook(self):
        def hook(p):
            if self._local_sgd_steps > 1:
                return  # local phase: gradients stay local; step() syncs
            self._passes_left[id(p)] -= 1
            if self._passes_left[id(p)] == 0:
                self._handles[p] = self._allreduce_grad_async(p)
                self._passes_left[id(p)] = self._bpps
        return hook

    def _allreduce_grad_async(self, p):
        from horovod_tpu.torch.compression import TopKCompressor

        name = self._param_names.get(id(p))
        if p.grad.is_sparse:
            if not self._sparse_as_dense:
                self._sparse_params[id(p)] = p.grad.sparse_dim()
                return self._sparse_allgather_async(p, name)
            p.grad = p.grad.to_dense()
        if isinstance(self._compression, TopKCompressor) and \
                p.grad.is_floating_point():
            # Top-k with error feedback: deferred to synchronize() — the
            # sparse path is two allgathers plus a host scatter-add, and
            # the residual buffer is keyed by this param's NAME (one per
            # gradient leaf, epoch-stamped in runtime.sparse).
            return ("topk", p)
        # Engine-wire compression (Compression.wire_*): the tensor stays
        # fp32; the engine quantizes on the ring.  The statistics-driven
        # wire policy (HOROVOD_WIRE_POLICY=1) refines the format per
        # leaf, stamped ADVISORY so per-rank statistics cannot split
        # negotiation.
        wire = getattr(self._compression, "engine_wire_dtype", None)
        advisory = False
        if self._wire_policy is not None and name is not None and \
                p.grad.is_floating_point() and not p.grad.is_sparse:
            chosen = self._wire_policy.observe_and_choose(
                name, p.grad.detach().cpu().numpy())
            if chosen is not None:
                wire = chosen
                advisory = True
        tensor_compressed, ctx = self._compression.compress(p.grad.data)
        priority = self._param_priority.get(id(p))
        if tensor_compressed.data_ptr() == p.grad.data.data_ptr():
            # In-place reduce directly into .grad when uncompressed.
            handle = allreduce_async_(tensor_compressed, average=True,
                                      name=name, wire_dtype=wire,
                                      priority=priority,
                                      wire_advisory=advisory)
        else:
            handle = allreduce_async_(
                tensor_compressed.contiguous(), average=True, name=name,
                wire_dtype=wire, priority=priority,
                wire_advisory=advisory)
        return handle, tensor_compressed, ctx

    def _sparse_allgather_async(self, p, name):
        """Gather-based sparse aggregation: allgather(indices) +
        allgather(values), summed by index on apply — memory-sane for large
        embeddings, where densifying would materialize the full table.
        Reference: ``tf.IndexedSlices`` handled as allgather of values and
        indices (tensorflow/__init__.py:67-78); the ragged per-rank nnz
        rides the engine's negotiated-dim-0 allgather."""
        g = p.grad.coalesce()
        idx = g.indices().t().contiguous()   # nnz x sparse_ndim, int64
        vals = g.values().contiguous()       # nnz x dense_dims
        h_idx = allgather_async(idx, name=f"{name}.idx" if name else None)
        h_val = allgather_async(vals, name=f"{name}.vals" if name else None)
        return ("sparse", h_idx, h_val)

    def _zero_sparse_grad(self, p, sd):
        return torch.sparse_coo_tensor(
            torch.zeros((sd, 0), dtype=torch.int64),
            p.data.new_zeros((0,) + p.shape[sd:]),
            size=p.shape)

    def _finish_sparse(self, p, h_idx, h_val):
        idx_all = synchronize(h_idx)
        val_all = synchronize(h_val)
        # coalesce() sums duplicate indices across ranks; divide for the
        # same average semantics as the dense path.
        p.grad = torch.sparse_coo_tensor(
            idx_all.t(), val_all / size(), size=p.shape,
            dtype=val_all.dtype).coalesce()

    def synchronize(self):
        """Finish all gradient allreduces and write results into ``.grad``
        (reference torch/__init__.py:98-108).  Parameters whose hook never
        fired (no grad this step) are still allreduced so ranks cannot
        deadlock (the force-allreduce contract, reference test_torch.py
        test_force_allreduce).  A param that ever produced a sparse grad
        takes the sparse gather path here too (with zero entries), so the
        collective names stay consistent with ranks whose hook did fire.
        A param whose layout is still UNKNOWN (hook never fired on this
        rank, e.g. the very first step of a data-dependent architecture)
        goes out as a wire-level layout PROBE: it completes as a dense
        zero allreduce unless peers are gathering it sparsely, in which
        case the coordinator answers SPARSE_RETRY and this rank joins the
        peers' '.idx'/'.vals' allgathers with zero entries — no warmup
        step needed, no stall."""
        from horovod_tpu.torch.compression import TopKCompressor

        topk_mode = isinstance(self._compression, TopKCompressor)
        for group in self.param_groups:
            for p in group["params"]:
                if p.requires_grad and p not in self._handles:
                    if p.grad is None:
                        sd = self._sparse_params.get(id(p))
                        if sd is not None:
                            p.grad = self._zero_sparse_grad(p, sd)
                        else:
                            p.grad = p.data.new_zeros(p.shape)
                            # No layout probe under top-k: peers submit
                            # '<name>.topk_idx'/'.topk_val' allgathers a
                            # dense probe could never rendezvous with.
                            # A zero gradient takes the topk path like
                            # everyone else (it ships top-k of its own
                            # residual — exactly the EF semantics).
                            if not self._sparse_as_dense and not topk_mode:
                                self._handles[p] = self._probe_grad_async(p)
                                continue
                    self._handles[p] = self._allreduce_grad_async(p)
        from horovod_tpu.runtime.engine import SparseGradRetry, StepSkipped

        # Backup-worker partial commits: a skipped gradient raises
        # StepSkipped, but the BATCH must still drain completely (an
        # abandoned handle leaks its kept-alive tensor and leaves
        # _handles stale for the next step) — collect the first skip and
        # re-raise only after every handle finished.
        first_skip = None
        topk_params = []
        for p, entry in self._handles.items():
            if entry[0] == "sparse":
                _, h_idx, h_val = entry
                self._finish_sparse(p, h_idx, h_val)
            elif entry[0] == "topk":
                # Deferred: the sparse allreduce is BLOCKING (two
                # allgathers per param), and _handles insertion order
                # follows this rank's hook-fire order — which a
                # data-dependent graph may permute across ranks.  All
                # topk params drain below in name-sorted order so every
                # rank submits the same collective sequence.
                topk_params.append(p)
            elif entry[0] == "probe":
                _, handle, tensor_compressed, ctx = entry
                try:
                    output = synchronize(handle)
                    p.grad.data.set_(
                        self._compression.decompress(output, ctx).data)
                except SparseGradRetry as retry:
                    self._sparse_params[id(p)] = retry.sparse_dim
                    p.grad = self._zero_sparse_grad(p, retry.sparse_dim)
                    _, h_idx, h_val = self._sparse_allgather_async(
                        p, self._param_names.get(id(p)))
                    self._finish_sparse(p, h_idx, h_val)
                except StepSkipped as skip:
                    if first_skip is None:
                        first_skip = skip
            else:
                handle, tensor_compressed, ctx = entry
                try:
                    output = synchronize(handle)
                except StepSkipped as skip:
                    if first_skip is None:
                        first_skip = skip
                    continue  # .grad keeps the local gradient
                p.grad.data.set_(
                    self._compression.decompress(output, ctx).data)
        if topk_params:
            from horovod_tpu.runtime.sparse import sparse_allreduce_topk

            def _topk_name(p):
                name = self._param_names.get(id(p))
                if not name:
                    # Never fall back to an id-derived name: ids differ
                    # across ranks, so the allgather rendezvous would
                    # wedge until the stall detector fires.
                    raise ValueError(
                        "top-k compression requires every parameter to "
                        "have a cross-rank-stable name (pass "
                        "named_parameters=...)")
                return name

            for p in sorted(topk_params, key=_topk_name):
                out = sparse_allreduce_topk(
                    p.grad.detach().cpu().numpy(), name=_topk_name(p),
                    ratio=self._compression.ratio,
                    error_feedback=self._compression.error_feedback,
                    average=True)
                p.grad.data.copy_(torch.from_numpy(out))
        self._handles.clear()
        if first_skip is not None:
            raise first_skip  # batch fully drained: clean per-step skip

    def _probe_grad_async(self, p):
        """Layout-probe for a param with no grad and no recorded layout:
        same name and compression as the dense hook path, flagged on the
        wire so a sparse/dense conflict resolves instead of stalling."""
        from horovod_tpu.torch.mpi_ops import _probe_allreduce_async_

        name = self._param_names.get(id(p))
        tensor_compressed, ctx = self._compression.compress(p.grad.data)
        handle = _probe_allreduce_async_(tensor_compressed.contiguous(),
                                         name)
        return ("probe", handle, tensor_compressed, ctx)

    def _named_param_tree(self):
        """Name-keyed host tree of the current params (the local-SGD
        policy's unit of anchoring and syncing)."""
        named = []
        for group in self.param_groups:
            for p in group["params"]:
                name = self._param_names.get(id(p))
                if name is None:
                    name = f"localsgd.p{len(named)}"
                named.append((name, p))
        return named, {n: p.data.detach().cpu().numpy() for n, p in named}

    def _local_sgd_maybe_sync(self):
        """Outer local-SGD sync (every H-th step): collect params into a
        name-keyed numpy tree, run the policy, and copy synced values
        back in place.  The policy re-anchors on an elastic epoch change
        and rides out backup-worker skips (reconstruction is anchor-free
        — see elastic.LocalSGD)."""
        import numpy as np

        named, tree = self._named_param_tree()
        synced = self._local_sgd.maybe_sync(tree)
        if synced is not tree:  # a sync happened: adopt the outer model
            with torch.no_grad():
                for n, p in named:
                    p.data.copy_(torch.from_numpy(
                        np.ascontiguousarray(synced[n])).to(p.dtype))

    def step(self, closure=None):
        if self._local_sgd_steps > 1:
            # Local-SGD phase: no gradient allreduce; apply the inner
            # optimizer locally, then let the policy decide whether this
            # is the H-th step (one outer sync).  Anchor the cadence
            # WITH THE PRE-STEP PARAMS before the first inner step: under
            # top-k the anchor VALUES are load-bearing (reconstruction is
            # anchor + avg(delta)), and the pre-training params are the
            # last cross-rank-identical state — anchoring after the first
            # purely-local step would bake each rank's own offset into
            # every future sync and the models would never reconverge.
            if not self._local_sgd._anchored:
                self._local_sgd.begin(self._named_param_tree()[1])
            loss = super(self.__class__, self).step(closure)
            self._local_sgd_maybe_sync()
            return loss
        self.synchronize()
        return super(self.__class__, self).step(closure)


class _ShardedOptimizer:
    """ZeRO-1 sharded optimizer (``DistributedOptimizer(sharded=True)``).

    Flattens EACH param group into its own fp32 master vector, keeps
    THIS rank's shard of each (and ONE inner optimizer instance of the
    user's class spanning the master shards, one inner group per user
    group — ~1/N of the optimizer-state and master-weight memory), and
    steps via the engine's collective halves per group:

        reducescatter(flat fp32 grads)   # half an allreduce's bytes
        inner.step() on the owned shard  # elementwise optimizer math
        allgather(updated master shard)  # full params back everywhere

    Mixed precision falls out naturally: model params may be fp16/bf16 —
    gradients are cast up to fp32 for the reduction, the update runs on
    the fp32 MASTER shard, and the gathered master is cast back into the
    model params.  For fp32 models with an elementwise inner optimizer
    the step is bit-identical to the equivalent unsharded flat step
    (asserted in tests/sharded_worker.py).

    Not the hook-mixin: gradients must all exist before the flat
    reduce-scatter, so the single collective fires in ``step()`` (the
    ZeRO trade: one flat RS instead of per-tensor overlap).  For LR
    schedulers, build them on :attr:`shard_optimizer` (the real
    ``torch.optim.Optimizer`` over the master shard — torch schedulers
    type-check their argument, and this wrapper is not an Optimizer
    subclass); ``param_groups`` aliases its groups, so manual
    ``param_groups[0]["lr"] = ...`` updates work on either handle.
    """

    def __init__(self, optimizer, compression=Compression.none):
        import numpy as np

        from horovod_tpu.runtime.sharded import FlatSharder

        wire = getattr(compression, "engine_wire_dtype", None)
        self._wire = wire if wire in ("fp16", "bf16", "int8", "fp8") \
            else None
        from horovod_tpu.torch.compression import TopKCompressor
        if isinstance(compression, TopKCompressor):
            raise ValueError(
                "sharded=True reduces gradients with reducescatter; the "
                "top-k sparse path has no scatter half — use a wire "
                "compressor (Compression.wire_bf16 etc.) instead")
        # Each param group shards INDEPENDENTLY: its own flat vector,
        # its own FlatSharder (distinct collective names by construction
        # order), its own fp32 master shard — so per-group
        # hyperparameters (lr, weight decay, momentum) never cross a
        # shard boundary, and LR schedulers keep their per-group
        # semantics on shard_optimizer.param_groups.
        self._groups = []
        shard_groups = []
        for gi, group in enumerate(optimizer.param_groups):
            params = list(group["params"])
            numels = [p.numel() for p in params]
            n = int(sum(numels))
            sharder = FlatSharder(n, np.float32,
                                  name=f"zero.torch.g{gi}")
            # fp32 master shard: the ONLY full-precision copy of this
            # slice in the world (ZeRO's master-weight sharding).
            with torch.no_grad():
                flat = torch.cat([
                    p.detach().to(torch.float32).reshape(-1)
                    for p in params
                ]) if params else torch.zeros(0)
                master = flat[
                    sharder.offset:
                    sharder.offset + sharder.count].clone()
            self._groups.append({
                "params": params,
                "shapes": [tuple(p.shape) for p in params],
                "numels": numels,
                "sharder": sharder,
                "master": master,
            })
            defaults = {k: v for k, v in group.items() if k != "params"}
            shard_groups.append({**defaults, "params": [master]})
        # ONE inner optimizer instance spanning every group's master
        # shard: torch optimizers accept per-group dicts, so group
        # hyperparameters ride through unchanged and one .step() covers
        # the whole model.
        self._shard_opt = type(optimizer)(shard_groups)
        #: The shard optimizer's groups — LR schedulers mutate the
        #: hyperparameters that actually drive the update (one group
        #: here per user group, same order).
        self.param_groups = self._shard_opt.param_groups

    @property
    def sharder(self):
        """Group 0's flat partitioner (shard offset/count, world anchor)
        — kept for back-compat; per-group access via :attr:`sharders`."""
        return self._groups[0]["sharder"] if self._groups else None

    @property
    def sharders(self):
        """Every group's flat partitioner, in group order."""
        return [g["sharder"] for g in self._groups]

    @property
    def shard_optimizer(self):
        """The inner ``torch.optim.Optimizer`` instance over the fp32
        master shard — the handle to give LR schedulers (its
        hyperparameters are the ones that drive the update;
        ``param_groups`` is the same object)."""
        return self._shard_opt

    def state_bytes(self) -> int:
        """Bytes of per-rank optimizer state + master weights (the ~1/N
        memory claim, measured: tests assert it)."""
        total = 0
        for g in self._groups:
            total += g["master"].numel() * g["master"].element_size()
        for st in self._shard_opt.state.values():
            for v in st.values():
                if torch.is_tensor(v):
                    total += v.numel() * v.element_size()
        return total

    def zero_grad(self, set_to_none: bool = True):
        for g in self._groups:
            for p in g["params"]:
                if set_to_none:
                    p.grad = None
                elif p.grad is not None:
                    p.grad.detach_()
                    p.grad.zero_()

    def step(self, closure=None):
        import numpy as np

        from horovod_tpu.runtime.engine import note_sharded_step

        loss = closure() if closure is not None else None

        def flat_grad(p, numel):
            if p.grad is None:
                return np.zeros(numel, dtype=np.float32)
            g = p.grad
            if g.is_sparse:
                g = g.to_dense()  # flat RS has no sparse path
            return np.ascontiguousarray(
                g.detach().to(torch.float32).reshape(-1).numpy())

        # Phase 1: every group's gradient reduce-scatter lands on its
        # master shard's .grad — all reductions complete before any
        # update, so ONE inner .step() then covers every group (torch
        # optimizers skip grad-less params, but here none are).
        for g in self._groups:
            flat_g = np.concatenate([
                flat_grad(p, numel)
                for p, numel in zip(g["params"], g["numels"])
            ]) if g["params"] else np.zeros(0, dtype=np.float32)
            shard_g = g["sharder"].reduce_grads(
                flat_g, average=True, wire_dtype=self._wire)
            g["master"].grad = torch.from_numpy(
                np.ascontiguousarray(shard_g))
        self._shard_opt.step()
        # Phase 2: ship each group's UPDATED master shard (not a delta —
        # the allgather is lossless, so every rank reconstructs the
        # identical new flat master) and copy it back into the params.
        for g in self._groups:
            g["master"].grad = None
            full = g["sharder"].gather_updates(
                g["master"].detach().numpy())
            with torch.no_grad():
                off = 0
                for p, numel, shape in zip(g["params"], g["numels"],
                                           g["shapes"]):
                    chunk = torch.from_numpy(
                        np.ascontiguousarray(full[off:off + numel]))
                    p.data.copy_(chunk.reshape(shape).to(p.dtype))
                    off += numel
        note_sharded_step()
        return loss

    def state_dict(self):
        """Shard-LOCAL state (each rank saves its own shards — see
        docs/checkpointing.md for the sharded save/restore recipe).
        Per-group geometry rides along so a reload at a different world
        size / group layout fails loudly."""
        return {
            "shard_opt": self._shard_opt.state_dict(),
            "groups": [
                {
                    "master": g["master"].detach().cpu(),
                    "shard": {"offset": g["sharder"].offset,
                              "count": g["sharder"].count,
                              "n": g["sharder"].n,
                              "size": g["sharder"].size},
                }
                for g in self._groups
            ],
        }

    def load_state_dict(self, sd):
        from horovod_tpu.runtime.sharded import ShardResizeError

        # PR 12's single-group format carried top-level master/shard;
        # accept it for a single-group optimizer.
        groups_sd = sd.get("groups")
        if groups_sd is None and "master" in sd:
            groups_sd = [{"master": sd["master"],
                          "shard": sd.get("shard", {})}]
        if groups_sd is None or len(groups_sd) != len(self._groups):
            raise ShardResizeError(
                "sharded checkpoint holds "
                f"{0 if groups_sd is None else len(groups_sd)} param "
                f"group(s) but this optimizer has {len(self._groups)}; "
                "the group layout must match the checkpoint's "
                "(docs/zero.md)")
        for gi, (g, gsd) in enumerate(zip(self._groups, groups_sd)):
            meta = gsd.get("shard", {})
            sh = g["sharder"]
            if (meta.get("n") != sh.n or meta.get("size") != sh.size or
                    meta.get("offset") != sh.offset):
                raise ShardResizeError(
                    f"sharded checkpoint group {gi} was written for "
                    f"shard {meta.get('offset')}+{meta.get('count')} of "
                    f"{meta.get('n')} at world size {meta.get('size')}, "
                    f"but this optimizer owns {sh.offset}+{sh.count} of "
                    f"{sh.n} at size {sh.size}; restore at the original "
                    "world size or rebuild from a full checkpoint "
                    "(docs/zero.md)")
        self._shard_opt.load_state_dict(sd["shard_opt"])
        with torch.no_grad():
            for g, gsd in zip(self._groups, groups_sd):
                g["master"].copy_(gsd["master"].to(torch.float32))


class _FsdpOptimizer:
    """ZeRO-3/FSDP optimizer (``DistributedOptimizer(fsdp=True)``).

    One step up the ladder from :class:`_ShardedOptimizer`: each param
    group is an FSDP **unit** on a :class:`~horovod_tpu.runtime.fsdp.
    FsdpPlane` window, and the backward pass drives the wire.  Grad
    hooks count a unit's outstanding leaves; the moment the LAST leaf
    of a unit lands, the unit's fp32 flat gradient reducescatters
    IMMEDIATELY (priority band = group index — front groups win the
    wire because the next forward needs them first) and the unit's
    ``.grad`` tensors are freed on the spot, so full-model gradient
    memory never materializes.  ``step()`` drains the reductions onto
    fp32 master shards (the masters ARE the plane's shards —
    ``torch.from_numpy`` write-through, so checkpoint capture sees live
    bytes), runs ONE inner step of the user's optimizer class across
    all master shards, then ships every group's updated master back
    through the plane's band-0 allgather pipeline (counted in
    ``fsdp_ag_prefetch_hits/misses``) and casts into the model params.

    Mixed precision like ZeRO-1: model params may be fp16/bf16; grads
    cast up for the reduction, the update runs on the fp32 master
    shard, and the gathered master casts back.  fp32 models with an
    elementwise inner optimizer step bit-identically to the unsharded
    anchor (asserted in tests/fsdp_worker.py).  For LR schedulers use
    :attr:`shard_optimizer`, as with the sharded optimizer.
    """

    def __init__(self, optimizer, compression=Compression.none,
                 prefetch=None):
        import numpy as np

        from horovod_tpu.runtime.fsdp import FsdpPlane
        from horovod_tpu.torch.compression import TopKCompressor

        if isinstance(compression, TopKCompressor):
            raise ValueError(
                "fsdp=True reduces gradients with reducescatter; the "
                "top-k sparse path has no scatter half — use a wire "
                "compressor (Compression.wire_bf16 etc.) instead")
        wire = getattr(compression, "engine_wire_dtype", None)
        wire = wire if wire in ("fp16", "bf16", "int8", "fp8") else None
        self._groups = []
        unit_params = []
        for group in optimizer.param_groups:
            params = list(group["params"])
            if not params:
                raise ValueError(
                    "fsdp=True: every param group must be non-empty "
                    "(each group is one FSDP unit)")
            self._groups.append({
                "params": params,
                "shapes": [tuple(p.shape) for p in params],
                "numels": [p.numel() for p in params],
                "defaults": {k: v for k, v in group.items()
                             if k != "params"},
            })
            unit_params.append([
                np.ascontiguousarray(
                    p.detach().to(torch.float32).reshape(-1).numpy())
                for p in params
            ])
        #: The parameter plane: unit = param group, shards fp32.
        self.plane = FsdpPlane(unit_params, name="torch",
                               prefetch=prefetch, wire_dtype=wire,
                               average=True)
        shard_groups = []
        for gi, g in enumerate(self._groups):
            # Write-through master: the torch tensor SHARES the plane
            # shard's buffer, so the inner optimizer's in-place update
            # IS the plane update (gathers and checkpoints see it).
            g["master"] = torch.from_numpy(self.plane.shard(gi))
            shard_groups.append({**g["defaults"],
                                 "params": [g["master"]]})
        self._shard_opt = type(optimizer)(shard_groups)
        self.param_groups = self._shard_opt.param_groups
        # Hook pipeline: fire a unit's RS the moment its last grad
        # lands (the backward cascade — no wait-for-full-model).
        self._pending = [0] * len(self._groups)
        self._enqueued = [False] * len(self._groups)
        self._grad_accs = []
        for gi, g in enumerate(self._groups):
            for p in g["params"]:
                if p.requires_grad:
                    self._pending[gi] += 1
                    self._grad_accs.append(
                        p.register_post_accumulate_grad_hook(
                            self._make_hook(gi)))
        self._hook_total = list(self._pending)

    def _make_hook(self, gi):
        def hook(p):
            self._pending[gi] -= 1
            if self._pending[gi] == 0:
                self._reduce_unit(gi)
        return hook

    def _reduce_unit(self, gi):
        import numpy as np

        g = self._groups[gi]
        flats = []
        for p, numel in zip(g["params"], g["numels"]):
            if p.grad is None:
                flats.append(np.zeros(numel, dtype=np.float32))
                continue
            gr = p.grad
            if gr.is_sparse:
                gr = gr.to_dense()  # flat RS has no sparse path
            flats.append(np.ascontiguousarray(
                gr.detach().to(torch.float32).reshape(-1).numpy()))
            # ZeRO-3 gradient hygiene: the full-precision grad is on
            # the wire now — drop the tensor before the NEXT unit's
            # backward allocates, so grad memory stays one-unit-deep.
            p.grad = None
        self.plane.reduce_grads(gi, flats)
        self._enqueued[gi] = True

    @property
    def shard_optimizer(self):
        """The inner ``torch.optim.Optimizer`` over the fp32 master
        shards — the handle to give LR schedulers."""
        return self._shard_opt

    @property
    def sharders(self):
        return [u.sharder for u in self.plane.units]

    def state_bytes(self) -> int:
        """Per-rank master-weight + optimizer-state bytes (the ~1/N
        memory claim)."""
        total = self.plane.shard_bytes
        for st in self._shard_opt.state.values():
            for v in st.values():
                if torch.is_tensor(v):
                    total += v.numel() * v.element_size()
        return total

    def zero_grad(self, set_to_none: bool = True):
        for g in self._groups:
            for p in g["params"]:
                if set_to_none:
                    p.grad = None
                elif p.grad is not None:
                    p.grad.detach_()
                    p.grad.zero_()

    def step(self, closure=None):
        import numpy as np

        loss = closure() if closure is not None else None
        # Units whose hooks never all fired this step (partial backward,
        # or grad-accumulation edge): reduce them NOW with zeros for the
        # missing leaves — the collective schedule must be identical on
        # every rank.
        for gi in range(len(self._groups)):
            if not self._enqueued[gi]:
                self._reduce_unit(gi)
        try:
            for gi, g in enumerate(self._groups):
                shard_g = self.plane.wait_grads(gi)
                g["master"].grad = torch.from_numpy(
                    np.ascontiguousarray(shard_g))
        except BaseException:
            self.plane.drain()  # never strand a later unit's handle
            self._reset_step()
            raise
        self._shard_opt.step()
        for g in self._groups:
            g["master"].grad = None
        # Ship every group's updated master through the plane's band-0
        # gather pipeline; copy back as each unit lands, free at once.
        for gi in range(len(self._groups)):
            self.plane.start_gather(gi, priority=0)
        for gi, g in enumerate(self._groups):
            fulls = self.plane.gather(gi)
            with torch.no_grad():
                for p, full, shape in zip(g["params"], fulls,
                                          g["shapes"]):
                    chunk = torch.from_numpy(np.ascontiguousarray(full))
                    p.data.copy_(chunk.reshape(shape).to(p.dtype))
            self.plane.free(gi)
        self._reset_step()
        self.plane.step()
        return loss

    def _reset_step(self):
        self._pending = list(self._hook_total)
        self._enqueued = [False] * len(self._groups)

    def state_dict(self):
        """Shard-LOCAL state (same envelope as the ZeRO-1 sharded
        optimizer: each rank saves its own windows)."""
        return {
            "shard_opt": self._shard_opt.state_dict(),
            "groups": [
                {
                    "master": g["master"].detach().clone().cpu(),
                    "shard": {"offset": u.sharder.offset,
                              "count": u.sharder.count,
                              "n": u.sharder.n,
                              "size": u.sharder.size},
                }
                for g, u in zip(self._groups, self.plane.units)
            ],
        }

    def load_state_dict(self, sd):
        from horovod_tpu.runtime.sharded import ShardResizeError

        groups_sd = sd.get("groups")
        if groups_sd is None or len(groups_sd) != len(self._groups):
            raise ShardResizeError(
                "fsdp checkpoint holds "
                f"{0 if groups_sd is None else len(groups_sd)} "
                f"unit(s) but this optimizer has {len(self._groups)}")
        for gi, (u, gsd) in enumerate(zip(self.plane.units, groups_sd)):
            meta = gsd.get("shard", {})
            sh = u.sharder
            if (meta.get("n") != sh.n or meta.get("size") != sh.size or
                    meta.get("offset") != sh.offset):
                raise ShardResizeError(
                    f"fsdp checkpoint unit {gi} was written for shard "
                    f"{meta.get('offset')}+{meta.get('count')} of "
                    f"{meta.get('n')} at world size {meta.get('size')}, "
                    f"but this optimizer owns {sh.offset}+{sh.count} of "
                    f"{sh.n} at size {sh.size}; restore through the "
                    "CheckpointLoader's resharding reader instead "
                    "(docs/zero.md)")
        self._shard_opt.load_state_dict(sd["shard_opt"])
        with torch.no_grad():
            for g, gsd in zip(self._groups, groups_sd):
                g["master"].copy_(gsd["master"].to(torch.float32))


def DistributedOptimizer(optimizer, named_parameters=None,
                         compression=Compression.none,
                         backward_passes_per_step=1,
                         sparse_as_dense=False,
                         local_sgd_steps=None,
                         sharded=None, fsdp=None, fsdp_prefetch=None):
    """Wrap a torch optimizer so gradients are averaged across ranks during
    ``backward()`` (reference factory, torch/__init__.py:115-150).

    Sparse gradients (e.g. from ``nn.Embedding(sparse=True)``) are
    aggregated by default via allgather(indices)+allgather(values) — the
    memory-sane path for large embedding tables (reference
    tensorflow/__init__.py:67-78) — and stay sparse in ``.grad``;
    ``sparse_as_dense=True`` densifies them before an ordinary allreduce
    instead (reference option, tensorflow/__init__.py:189-199).

    ``local_sgd_steps=H`` (default ``HOROVOD_LOCAL_SGD_STEPS``, 1)
    switches to communication-relaxed local SGD: gradients apply purely
    locally and ``step()`` allreduces the MODEL delta once every ``H``
    steps (epoch-stamped — an elastic resize re-anchors instead of
    leaking a dead incarnation's delta).  ``H <= 1`` keeps the per-step
    gradient-allreduce path byte-identical.  With
    ``compression=Compression.topk(ratio)`` the outer sync ships the
    model delta through the top-k sparse path (its own epoch-stamped
    error-feedback residuals).

    ``sharded=True`` (default ``HOROVOD_SHARDED``) returns the ZeRO-1
    :class:`_ShardedOptimizer` instead of the hook mixin: fp32 master
    weights and optimizer state live only on each shard's owner (~1/N
    memory), gradients reduce by ``reducescatter`` and params return by
    ``allgather`` — see docs/zero.md.

    ``fsdp=True`` (default ``HOROVOD_FSDP``) returns the ZeRO-3
    :class:`_FsdpOptimizer`: each param group is a parameter-plane unit
    whose gradient reducescatter fires FROM THE GRAD HOOK the moment
    the unit's last leaf lands (grads freed immediately — one-unit-deep
    gradient memory), and updated master shards return through band-0
    allgathers (``fsdp_prefetch``, default ``HOROVOD_FSDP_PREFETCH``)
    — see docs/zero.md's sharding ladder."""
    from horovod_tpu.runtime.fsdp import fsdp_default
    from horovod_tpu.runtime.sharded import sharded_default

    if sharded is None:
        sharded = sharded_default()
    if fsdp is None:
        fsdp = fsdp_default()
    if fsdp and sharded:
        raise ValueError(
            "fsdp=True and sharded=True are mutually exclusive: FSDP "
            "subsumes the ZeRO-1 step (pick one rung of the ladder; "
            "see docs/zero.md)")
    if sharded or fsdp:
        from horovod_tpu.elastic.state import default_local_sgd_steps

        which = "fsdp=True" if fsdp else "sharded=True"
        # Resolve the env default too (HOROVOD_LOCAL_SGD_STEPS) so the
        # exclusivity contract matches the jax frontend's: a requested
        # local-SGD cadence must never be silently dropped.
        resolved_h = (default_local_sgd_steps() if local_sgd_steps is None
                      else max(1, int(local_sgd_steps)))
        if resolved_h > 1:
            raise ValueError(
                f"{which} and local_sgd_steps>1 are mutually "
                "exclusive: local SGD skips the per-step reduction the "
                "sharded step is built around")
        if int(backward_passes_per_step) != 1:
            # Never silently change gradient-accumulation semantics: the
            # sharded step reduces+applies on EVERY step().
            raise ValueError(
                f"{which} does not support backward_passes_per_step"
                f"={backward_passes_per_step}: the flat reduce-scatter "
                "fires on every step(). Accumulate gradients in the "
                "training loop (call step() every Nth backward) instead")
        # named_parameters is accepted and unused (the flat RS needs no
        # per-tensor names); sparse grads are densified in step().
        if fsdp:
            return _FsdpOptimizer(optimizer, compression=compression,
                                  prefetch=fsdp_prefetch)
        return _ShardedOptimizer(optimizer, compression=compression)
    cls = type(optimizer.__class__.__name__, (optimizer.__class__,),
               dict(_DistributedOptimizer.__dict__))
    return cls(optimizer.param_groups, named_parameters, compression,
               backward_passes_per_step, sparse_as_dense, local_sgd_steps)


def broadcast_parameters(params, root_rank: int = 0):
    """Broadcast a state_dict or list of (name, tensor) from root to all
    (reference torch/__init__.py:153-182)."""
    if isinstance(params, dict):
        items = sorted(params.items())
    else:
        items = list(params)
    handles = []
    for name, p in items:
        if p is None or not torch.is_tensor(p):
            continue
        handles.append(broadcast_async_(p, root_rank, name=f"bcastp.{name}"))
    for h in handles:
        synchronize(h)


def broadcast_optimizer_state(optimizer, root_rank: int = 0):
    """Broadcast optimizer state (momenta etc.) from root
    (reference torch/__init__.py:185-301): state on non-root ranks is first
    materialized with a zero-grad dummy step, scalar entries are
    tensor-ized for the wire and restored to native python types after."""
    if isinstance(optimizer, torch.optim.LBFGS):
        raise ValueError("cannot broadcast torch.optim.LBFGS state")
    state_dict = optimizer.state_dict()

    if len(state_dict["state"]) == 0:
        for group in optimizer.param_groups:
            for p in group["params"]:
                if p.requires_grad and p.grad is None:
                    p.grad = p.data.new_zeros(p.shape)
        # The dummy step must (a) stay LOCAL — on resume only the ranks
        # with un-restored state take this path, so the DistributedOptimizer
        # wrapper's step() would enqueue collectives the other ranks never
        # join — and (b) leave params untouched (weight decay moves params
        # even at zero grad, and the state broadcast below does not undo
        # param drift).
        saved = [p.detach().clone()
                 for group in optimizer.param_groups
                 for p in group["params"]]
        if hasattr(optimizer, "_allreduce_grad_async"):
            # Bypass the wrapper: MRO is (DynamicWrapper, UserOptimizer, …).
            type(optimizer).__mro__[1].step(optimizer)
        else:
            optimizer.step()
        it = iter(saved)
        with torch.no_grad():
            for group in optimizer.param_groups:
                for p in group["params"]:
                    p.data.copy_(next(it))
        state_dict = optimizer.state_dict()

    callbacks = {}
    occurrences = collections.defaultdict(int)

    def _name(base):
        occurrences[base] += 1
        return f"{base}.{occurrences[base]}"

    params_to_bcast = []

    def _tensorize(value, dict_key, base, holder):
        """Scalars travel as tensors; a callback restores the native type
        into ``holder[dict_key]`` (reference _create_option_callback /
        _create_state_callback)."""
        if torch.is_tensor(value):
            params_to_bcast.append((_name(base), value))
            return
        if isinstance(value, bool):
            t = torch.tensor(int(value))
            cast = lambda x: bool(x.item())  # noqa: E731
        elif isinstance(value, int):
            t = torch.tensor(value)
            cast = lambda x: int(x.item())  # noqa: E731
        elif isinstance(value, float):
            t = torch.tensor(value, dtype=torch.float64)
            cast = lambda x: float(x.item())  # noqa: E731
        else:
            return  # non-numeric options (None, str) assumed rank-consistent
        name = _name(base)
        params_to_bcast.append((name, t))
        callbacks[name] = (holder, dict_key, t, cast)

    for gi, group in enumerate(state_dict["param_groups"]):
        for key, value in sorted(group.items()):
            if key == "params":
                continue
            _tensorize(value, key, f"group.{gi}.{key}", group)
    for pid, pstate in sorted(state_dict["state"].items(),
                              key=lambda kv: str(kv[0])):
        for key, value in sorted(pstate.items()):
            _tensorize(value, key, f"state.{pid}.{key}", pstate)

    broadcast_parameters(params_to_bcast, root_rank)

    for name, (holder, dict_key, t, cast) in callbacks.items():
        holder[dict_key] = cast(t)
    optimizer.load_state_dict(state_dict)
