"""PyTorch frontend.

Reference parity: ``horovod/torch/__init__.py`` (301 LoC) —
``DistributedOptimizer`` (gradient hooks firing async allreduces during
backward, so communication overlaps remaining compute),
``broadcast_parameters`` and ``broadcast_optimizer_state`` (including the
scalar tensor-ization dance), plus the full op surface re-exported from
``mpi_ops``.

TPU context: torch runs on host CPU here (no CUDA in a TPU pod); this
frontend gives torch training scripts the same scaling API they had with
the reference, with the native engine's ring collectives over DCN as the
data plane.  The heavy-compute path on TPU is the JAX frontend; the torch
frontend exists for capability parity and host-side workloads.
"""

from __future__ import annotations

import collections
from typing import Optional

import torch

from horovod_tpu.common.basics import basics
from horovod_tpu.torch import bridge
from horovod_tpu.torch.compression import Compression
from horovod_tpu.torch.mpi_ops import (
    allgather,
    allgather_async,
    allreduce,
    allreduce_,
    allreduce_async,
    allreduce_async_,
    alltoall,
    alltoall_async,
    broadcast,
    broadcast_,
    broadcast_async,
    broadcast_async_,
    grouped_allreduce,
    grouped_allreduce_async,
    init,
    local_rank,
    local_size,
    poll,
    rank,
    reducescatter,
    reducescatter_async,
    shutdown,
    size,
    synchronize,
)

is_initialized = basics.is_initialized
epoch = basics.epoch
mpi_threads_supported = basics.mpi_threads_supported

__all__ = [
    "init", "shutdown", "is_initialized", "rank", "size", "local_rank",
    "local_size", "epoch", "mpi_threads_supported",
    "allreduce", "allreduce_async", "allreduce_", "allreduce_async_",
    "grouped_allreduce", "grouped_allreduce_async",
    "allgather", "allgather_async",
    "broadcast", "broadcast_async", "broadcast_", "broadcast_async_",
    "reducescatter", "reducescatter_async", "alltoall", "alltoall_async",
    "poll", "synchronize", "Compression", "bridge",
    "DistributedOptimizer", "broadcast_parameters",
    "broadcast_optimizer_state",
]


class _DistributedOptimizer(torch.optim.Optimizer):
    """Mixin pattern from the reference (torch/__init__.py:31-144):
    dynamically combined with the user's optimizer class so
    ``isinstance(opt, UserOptimizer)`` stays true and checkpoints load
    without this library installed."""

    def __init__(self, params, named_parameters=None,
                 compression=Compression.none,
                 backward_passes_per_step=1,
                 sparse_as_dense=False,
                 local_sgd_steps=None):
        from horovod_tpu.elastic.state import (LocalSGD,
                                               default_local_sgd_steps)

        super(self.__class__, self).__init__(params)
        self._compression = compression
        self._bpps = backward_passes_per_step
        self._sparse_as_dense = sparse_as_dense
        # Local SGD (DiLoCo-style periodic sync): H purely-local steps,
        # then one outer allreduce of the MODEL delta in step().  H <= 1
        # keeps the per-step gradient allreduce path byte-identical.
        self._local_sgd_steps = (default_local_sgd_steps()
                                 if local_sgd_steps is None
                                 else max(1, int(local_sgd_steps)))
        self._local_sgd = (LocalSGD(self._local_sgd_steps)
                           if self._local_sgd_steps > 1 else None)

        if named_parameters is not None:
            named_parameters = list(named_parameters)
        else:
            # Single running counter across param groups: per-group
            # numbering would hand two groups the same synthesized name,
            # and names are load-bearing for collective rendezvous.
            named_parameters = [
                (f"allreduce.noname.{i}", v)
                for i, v in enumerate(
                    v for param_group in self.param_groups
                    for v in param_group["params"])
            ]
        # Sanity checks mirroring the reference (torch/__init__.py:41-67).
        all_params = {
            id(v) for group in self.param_groups for v in group["params"]
        }
        named_ids = {id(v) for _, v in named_parameters}
        if len(named_parameters) != len(named_ids):
            raise ValueError("named_parameters contains duplicate parameters")
        unnamed = all_params - named_ids
        if unnamed and len(named_parameters) > 0 and named_ids != all_params:
            raise ValueError(
                f"named_parameters covers {len(named_ids)} parameters but "
                f"the optimizer has {len(all_params)}; provide names for all"
            )
        self._param_names = {id(v): k for k, v in named_parameters}

        self._handles: dict = {}
        self._grad_accs = []
        # id(param) -> sparse_dim for params that have produced a sparse
        # gradient: the force-allreduce fallback must keep using the sparse
        # gather path for them (a dense zero allreduce would never
        # rendezvous with peers' '<name>.idx'/'.vals' allgathers and the
        # job would stall).
        self._sparse_params: dict = {}
        self._passes_left = collections.defaultdict(
            lambda: self._bpps)
        # Hooks are registered at any size so behavior (incl. the
        # force-allreduce-in-step contract) is identical at any scale.
        self._register_hooks()

    # -- hook pipeline (reference torch/__init__.py:72-96) --

    def _register_hooks(self):
        for group in self.param_groups:
            for p in group["params"]:
                if p.requires_grad:
                    self._grad_accs.append(
                        p.register_post_accumulate_grad_hook(
                            self._make_hook()))

    def _make_hook(self):
        def hook(p):
            if self._local_sgd_steps > 1:
                return  # local phase: gradients stay local; step() syncs
            self._passes_left[id(p)] -= 1
            if self._passes_left[id(p)] == 0:
                self._handles[p] = self._allreduce_grad_async(p)
                self._passes_left[id(p)] = self._bpps
        return hook

    def _allreduce_grad_async(self, p):
        from horovod_tpu.torch.compression import TopKCompressor

        name = self._param_names.get(id(p))
        if p.grad.is_sparse:
            if not self._sparse_as_dense:
                self._sparse_params[id(p)] = p.grad.sparse_dim()
                return self._sparse_allgather_async(p, name)
            p.grad = p.grad.to_dense()
        if isinstance(self._compression, TopKCompressor) and \
                p.grad.is_floating_point():
            # Top-k with error feedback: deferred to synchronize() — the
            # sparse path is two allgathers plus a host scatter-add, and
            # the residual buffer is keyed by this param's NAME (one per
            # gradient leaf, epoch-stamped in runtime.sparse).
            return ("topk", p)
        # Engine-wire compression (Compression.wire_*): the tensor stays
        # fp32; the engine quantizes on the ring.
        wire = getattr(self._compression, "engine_wire_dtype", None)
        tensor_compressed, ctx = self._compression.compress(p.grad.data)
        if tensor_compressed.data_ptr() == p.grad.data.data_ptr():
            # In-place reduce directly into .grad when uncompressed.
            handle = allreduce_async_(tensor_compressed, average=True,
                                      name=name, wire_dtype=wire)
        else:
            handle = allreduce_async_(
                tensor_compressed.contiguous(), average=True, name=name,
                wire_dtype=wire)
        return handle, tensor_compressed, ctx

    def _sparse_allgather_async(self, p, name):
        """Gather-based sparse aggregation: allgather(indices) +
        allgather(values), summed by index on apply — memory-sane for large
        embeddings, where densifying would materialize the full table.
        Reference: ``tf.IndexedSlices`` handled as allgather of values and
        indices (tensorflow/__init__.py:67-78); the ragged per-rank nnz
        rides the engine's negotiated-dim-0 allgather."""
        g = p.grad.coalesce()
        idx = g.indices().t().contiguous()   # nnz x sparse_ndim, int64
        vals = g.values().contiguous()       # nnz x dense_dims
        h_idx = allgather_async(idx, name=f"{name}.idx" if name else None)
        h_val = allgather_async(vals, name=f"{name}.vals" if name else None)
        return ("sparse", h_idx, h_val)

    def _zero_sparse_grad(self, p, sd):
        return torch.sparse_coo_tensor(
            torch.zeros((sd, 0), dtype=torch.int64),
            p.data.new_zeros((0,) + p.shape[sd:]),
            size=p.shape)

    def _finish_sparse(self, p, h_idx, h_val):
        idx_all = synchronize(h_idx)
        val_all = synchronize(h_val)
        # coalesce() sums duplicate indices across ranks; divide for the
        # same average semantics as the dense path.
        p.grad = torch.sparse_coo_tensor(
            idx_all.t(), val_all / size(), size=p.shape,
            dtype=val_all.dtype).coalesce()

    def synchronize(self):
        """Finish all gradient allreduces and write results into ``.grad``
        (reference torch/__init__.py:98-108).  Parameters whose hook never
        fired (no grad this step) are still allreduced so ranks cannot
        deadlock (the force-allreduce contract, reference test_torch.py
        test_force_allreduce).  A param that ever produced a sparse grad
        takes the sparse gather path here too (with zero entries), so the
        collective names stay consistent with ranks whose hook did fire.
        A param whose layout is still UNKNOWN (hook never fired on this
        rank, e.g. the very first step of a data-dependent architecture)
        goes out as a wire-level layout PROBE: it completes as a dense
        zero allreduce unless peers are gathering it sparsely, in which
        case the coordinator answers SPARSE_RETRY and this rank joins the
        peers' '.idx'/'.vals' allgathers with zero entries — no warmup
        step needed, no stall."""
        from horovod_tpu.torch.compression import TopKCompressor

        topk_mode = isinstance(self._compression, TopKCompressor)
        for group in self.param_groups:
            for p in group["params"]:
                if p.requires_grad and p not in self._handles:
                    if p.grad is None:
                        sd = self._sparse_params.get(id(p))
                        if sd is not None:
                            p.grad = self._zero_sparse_grad(p, sd)
                        else:
                            p.grad = p.data.new_zeros(p.shape)
                            # No layout probe under top-k: peers submit
                            # '<name>.topk_idx'/'.topk_val' allgathers a
                            # dense probe could never rendezvous with.
                            # A zero gradient takes the topk path like
                            # everyone else (it ships top-k of its own
                            # residual — exactly the EF semantics).
                            if not self._sparse_as_dense and not topk_mode:
                                self._handles[p] = self._probe_grad_async(p)
                                continue
                    self._handles[p] = self._allreduce_grad_async(p)
        from horovod_tpu.runtime.engine import SparseGradRetry, StepSkipped

        # Backup-worker partial commits: a skipped gradient raises
        # StepSkipped, but the BATCH must still drain completely (an
        # abandoned handle leaks its kept-alive tensor and leaves
        # _handles stale for the next step) — collect the first skip and
        # re-raise only after every handle finished.
        first_skip = None
        topk_params = []
        for p, entry in self._handles.items():
            if entry[0] == "sparse":
                _, h_idx, h_val = entry
                self._finish_sparse(p, h_idx, h_val)
            elif entry[0] == "topk":
                # Deferred: the sparse allreduce is BLOCKING (two
                # allgathers per param), and _handles insertion order
                # follows this rank's hook-fire order — which a
                # data-dependent graph may permute across ranks.  All
                # topk params drain below in name-sorted order so every
                # rank submits the same collective sequence.
                topk_params.append(p)
            elif entry[0] == "probe":
                _, handle, tensor_compressed, ctx = entry
                try:
                    output = synchronize(handle)
                    p.grad.data.set_(
                        self._compression.decompress(output, ctx).data)
                except SparseGradRetry as retry:
                    self._sparse_params[id(p)] = retry.sparse_dim
                    p.grad = self._zero_sparse_grad(p, retry.sparse_dim)
                    _, h_idx, h_val = self._sparse_allgather_async(
                        p, self._param_names.get(id(p)))
                    self._finish_sparse(p, h_idx, h_val)
                except StepSkipped as skip:
                    if first_skip is None:
                        first_skip = skip
            else:
                handle, tensor_compressed, ctx = entry
                try:
                    output = synchronize(handle)
                except StepSkipped as skip:
                    if first_skip is None:
                        first_skip = skip
                    continue  # .grad keeps the local gradient
                p.grad.data.set_(
                    self._compression.decompress(output, ctx).data)
        if topk_params:
            from horovod_tpu.runtime.sparse import sparse_allreduce_topk

            def _topk_name(p):
                name = self._param_names.get(id(p))
                if not name:
                    # Never fall back to an id-derived name: ids differ
                    # across ranks, so the allgather rendezvous would
                    # wedge until the stall detector fires.
                    raise ValueError(
                        "top-k compression requires every parameter to "
                        "have a cross-rank-stable name (pass "
                        "named_parameters=...)")
                return name

            for p in sorted(topk_params, key=_topk_name):
                out = sparse_allreduce_topk(
                    p.grad.detach().cpu().numpy(), name=_topk_name(p),
                    ratio=self._compression.ratio,
                    error_feedback=self._compression.error_feedback,
                    average=True)
                p.grad.data.copy_(torch.from_numpy(out))
        self._handles.clear()
        if first_skip is not None:
            raise first_skip  # batch fully drained: clean per-step skip

    def _probe_grad_async(self, p):
        """Layout-probe for a param with no grad and no recorded layout:
        same name and compression as the dense hook path, flagged on the
        wire so a sparse/dense conflict resolves instead of stalling."""
        from horovod_tpu.torch.mpi_ops import _probe_allreduce_async_

        name = self._param_names.get(id(p))
        tensor_compressed, ctx = self._compression.compress(p.grad.data)
        handle = _probe_allreduce_async_(tensor_compressed.contiguous(),
                                         name)
        return ("probe", handle, tensor_compressed, ctx)

    def _local_sgd_maybe_sync(self):
        """Outer local-SGD sync (every H-th step): collect params into a
        name-keyed numpy tree, run the policy, and copy synced values
        back in place.  The policy re-anchors on an elastic epoch change
        and rides out backup-worker skips (reconstruction is anchor-free
        — see elastic.LocalSGD)."""
        import numpy as np

        named = []
        for group in self.param_groups:
            for p in group["params"]:
                name = self._param_names.get(id(p))
                if name is None:
                    name = f"localsgd.p{len(named)}"
                named.append((name, p))
        tree = {n: p.data.detach().cpu().numpy() for n, p in named}
        synced = self._local_sgd.maybe_sync(tree)
        if synced is not tree:  # a sync happened: adopt the outer model
            with torch.no_grad():
                for n, p in named:
                    p.data.copy_(torch.from_numpy(
                        np.ascontiguousarray(synced[n])).to(p.dtype))

    def step(self, closure=None):
        if self._local_sgd_steps > 1:
            # Local-SGD phase: no gradient allreduce; apply the inner
            # optimizer locally, then let the policy decide whether this
            # is the H-th step (one outer sync).  Anchor the cadence
            # BEFORE the first inner step so the first sync covers
            # exactly H local updates.
            if not self._local_sgd._anchored:
                self._local_sgd.begin()
            loss = super(self.__class__, self).step(closure)
            self._local_sgd_maybe_sync()
            return loss
        self.synchronize()
        return super(self.__class__, self).step(closure)


def DistributedOptimizer(optimizer, named_parameters=None,
                         compression=Compression.none,
                         backward_passes_per_step=1,
                         sparse_as_dense=False,
                         local_sgd_steps=None):
    """Wrap a torch optimizer so gradients are averaged across ranks during
    ``backward()`` (reference factory, torch/__init__.py:115-150).

    Sparse gradients (e.g. from ``nn.Embedding(sparse=True)``) are
    aggregated by default via allgather(indices)+allgather(values) — the
    memory-sane path for large embedding tables (reference
    tensorflow/__init__.py:67-78) — and stay sparse in ``.grad``;
    ``sparse_as_dense=True`` densifies them before an ordinary allreduce
    instead (reference option, tensorflow/__init__.py:189-199).

    ``local_sgd_steps=H`` (default ``HOROVOD_LOCAL_SGD_STEPS``, 1)
    switches to communication-relaxed local SGD: gradients apply purely
    locally and ``step()`` allreduces the MODEL delta once every ``H``
    steps (epoch-stamped — an elastic resize re-anchors instead of
    leaking a dead incarnation's delta).  ``H <= 1`` keeps the per-step
    gradient-allreduce path byte-identical."""
    cls = type(optimizer.__class__.__name__, (optimizer.__class__,),
               dict(_DistributedOptimizer.__dict__))
    return cls(optimizer.param_groups, named_parameters, compression,
               backward_passes_per_step, sparse_as_dense, local_sgd_steps)


def broadcast_parameters(params, root_rank: int = 0):
    """Broadcast a state_dict or list of (name, tensor) from root to all
    (reference torch/__init__.py:153-182)."""
    if isinstance(params, dict):
        items = sorted(params.items())
    else:
        items = list(params)
    handles = []
    for name, p in items:
        if p is None or not torch.is_tensor(p):
            continue
        handles.append(broadcast_async_(p, root_rank, name=f"bcastp.{name}"))
    for h in handles:
        synchronize(h)


def broadcast_optimizer_state(optimizer, root_rank: int = 0):
    """Broadcast optimizer state (momenta etc.) from root
    (reference torch/__init__.py:185-301): state on non-root ranks is first
    materialized with a zero-grad dummy step, scalar entries are
    tensor-ized for the wire and restored to native python types after."""
    if isinstance(optimizer, torch.optim.LBFGS):
        raise ValueError("cannot broadcast torch.optim.LBFGS state")
    state_dict = optimizer.state_dict()

    if len(state_dict["state"]) == 0:
        for group in optimizer.param_groups:
            for p in group["params"]:
                if p.requires_grad and p.grad is None:
                    p.grad = p.data.new_zeros(p.shape)
        # The dummy step must (a) stay LOCAL — on resume only the ranks
        # with un-restored state take this path, so the DistributedOptimizer
        # wrapper's step() would enqueue collectives the other ranks never
        # join — and (b) leave params untouched (weight decay moves params
        # even at zero grad, and the state broadcast below does not undo
        # param drift).
        saved = [p.detach().clone()
                 for group in optimizer.param_groups
                 for p in group["params"]]
        if hasattr(optimizer, "_allreduce_grad_async"):
            # Bypass the wrapper: MRO is (DynamicWrapper, UserOptimizer, …).
            type(optimizer).__mro__[1].step(optimizer)
        else:
            optimizer.step()
        it = iter(saved)
        with torch.no_grad():
            for group in optimizer.param_groups:
                for p in group["params"]:
                    p.data.copy_(next(it))
        state_dict = optimizer.state_dict()

    callbacks = {}
    occurrences = collections.defaultdict(int)

    def _name(base):
        occurrences[base] += 1
        return f"{base}.{occurrences[base]}"

    params_to_bcast = []

    def _tensorize(value, dict_key, base, holder):
        """Scalars travel as tensors; a callback restores the native type
        into ``holder[dict_key]`` (reference _create_option_callback /
        _create_state_callback)."""
        if torch.is_tensor(value):
            params_to_bcast.append((_name(base), value))
            return
        if isinstance(value, bool):
            t = torch.tensor(int(value))
            cast = lambda x: bool(x.item())  # noqa: E731
        elif isinstance(value, int):
            t = torch.tensor(value)
            cast = lambda x: int(x.item())  # noqa: E731
        elif isinstance(value, float):
            t = torch.tensor(value, dtype=torch.float64)
            cast = lambda x: float(x.item())  # noqa: E731
        else:
            return  # non-numeric options (None, str) assumed rank-consistent
        name = _name(base)
        params_to_bcast.append((name, t))
        callbacks[name] = (holder, dict_key, t, cast)

    for gi, group in enumerate(state_dict["param_groups"]):
        for key, value in sorted(group.items()):
            if key == "params":
                continue
            _tensorize(value, key, f"group.{gi}.{key}", group)
    for pid, pstate in sorted(state_dict["state"].items(),
                              key=lambda kv: str(kv[0])):
        for key, value in sorted(pstate.items()):
            _tensorize(value, key, f"state.{pid}.{key}", pstate)

    broadcast_parameters(params_to_bcast, root_rank)

    for name, (holder, dict_key, t, cast) in callbacks.items():
        holder[dict_key] = cast(t)
    optimizer.load_state_dict(state_dict)
