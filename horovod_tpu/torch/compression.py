"""Gradient compression for the torch frontend.

Reference parity: ``horovod/torch/compression.py`` (74 LoC) — a
``Compressor`` interface with ``none``/``fp16`` members; compress casts
floats down for the wire, decompress casts back.  Adds ``bf16``: on the
host data plane bf16 halves wire bytes with float32's exponent range, and
it round-trips exactly through the TPU compute dtype.
"""

from __future__ import annotations

import torch

__all__ = ["Compressor", "NoneCompressor", "FP16Compressor",
           "BF16Compressor", "WireCompressor", "TopKCompressor",
           "Compression"]


class Compressor:
    """Interface for compressing and decompressing a given tensor."""

    @staticmethod
    def compress(tensor):
        """Returns (compressed_tensor, ctx) where ctx feeds decompress."""
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class _CastCompressor(Compressor):
    wire_dtype: torch.dtype

    @classmethod
    def compress(cls, tensor):
        if tensor.is_floating_point() and tensor.dtype != cls.wire_dtype:
            return tensor.to(cls.wire_dtype), tensor.dtype
        return tensor, None

    @classmethod
    def decompress(cls, tensor, ctx):
        return tensor.to(ctx) if ctx is not None else tensor


class FP16Compressor(_CastCompressor):
    wire_dtype = torch.float16


class BF16Compressor(_CastCompressor):
    wire_dtype = torch.bfloat16


class WireCompressor(Compressor):
    """WIRE-level compression: identity on the tensor; the native engine
    carries per-chunk-scaled quantized bytes (HOROVOD_WIRE_DTYPE
    semantics, negotiated cross-rank) and hands back fp32."""

    engine_wire_dtype: str = "fp32"

    @classmethod
    def compress(cls, tensor):
        return tensor, None

    @classmethod
    def decompress(cls, tensor, ctx):
        return tensor


class _WireFP16(WireCompressor):
    engine_wire_dtype = "fp16"


class _WireBF16(WireCompressor):
    engine_wire_dtype = "bf16"


class _WireInt8(WireCompressor):
    engine_wire_dtype = "int8"


class _WireFP8(WireCompressor):
    engine_wire_dtype = "fp8"


class TopKCompressor:
    """Top-k sparse allreduce spec with error-feedback residuals, keyed
    per parameter name by ``DistributedOptimizer`` (the residual state
    lives in horovod_tpu.runtime.sparse, epoch-stamped so an elastic
    resize clears it)."""

    def __init__(self, ratio=None, error_feedback: bool = True):
        # None defers to the HOROVOD_SPARSE_TOPK env default (resolved
        # per call by sparse_allreduce_topk) — the documented knob.
        if ratio is not None and not 0.0 < ratio <= 1.0:
            raise ValueError(f"topk ratio must be in (0, 1], got {ratio}")
        self.ratio = float(ratio) if ratio is not None else None
        self.error_feedback = bool(error_feedback)

    def compress(self, tensor):
        return tensor, None

    def decompress(self, tensor, ctx):
        return tensor


class Compression:
    """Registry (reference compression.py:67-74).  ``fp16``/``bf16``
    cast the tensor itself; the ``wire_*`` members compress at the wire
    level inside the engine, and ``topk(ratio)`` selects the sparse
    error-feedback path per parameter."""

    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
    wire_fp16 = _WireFP16
    wire_bf16 = _WireBF16
    wire_int8 = _WireInt8
    wire_fp8 = _WireFP8

    @staticmethod
    def topk(ratio=None, error_feedback: bool = True) -> TopKCompressor:
        """``ratio=None`` defers to HOROVOD_SPARSE_TOPK (default 0.01)."""
        return TopKCompressor(ratio, error_feedback)
