"""Version of the horovod_tpu framework.

Reference parity target: Horovod 0.15.1 (``/root/reference/horovod/__init__.py:1``).
"""

__version__ = "0.1.0"
