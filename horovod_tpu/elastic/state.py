"""Commit/restore/sync of training state for elastic recovery.

Role parity with Elastic Horovod's ``hvd.elastic.State`` (commit /
restore / sync): the state object owns named slots (params, optimizer
state, step counter, ...), snapshots them to host numpy on ``commit()``,
rolls back on ``restore()``, and ``sync()`` broadcasts the current values
from a root so every rank — including a freshly relaunched worker —
proceeds from identical state.

Slots hold pytrees: arbitrarily nested dict / list / tuple (incl.
namedtuples, so raw optax states work) with array-like or scalar leaves.
Leaves are traversed in sorted-key order so cross-rank collective names
rendezvous deterministically.
"""

from __future__ import annotations

import os

import numpy as np

from horovod_tpu.runtime import engine_or_none

__all__ = ["ElasticState", "LocalSGD", "default_local_sgd_steps"]


def _host_copy(obj):
    """Deep copy a pytree with every array leaf as a host numpy copy."""
    if isinstance(obj, dict):
        return {k: _host_copy(v) for k, v in obj.items()}
    if isinstance(obj, tuple):
        vals = [_host_copy(v) for v in obj]
        if hasattr(obj, "_fields"):  # namedtuple (e.g. optax state)
            return type(obj)(*vals)
        return tuple(vals)
    if isinstance(obj, list):
        return [_host_copy(v) for v in obj]
    if obj is None or isinstance(obj, (bool, int, float, complex, str,
                                       bytes)):
        return obj
    # Array-like (numpy, jax, torch-on-cpu via __array__): materialize on
    # host, detached from any device buffer the engine might clobber.
    return np.array(np.asarray(obj), copy=True)


def _walk(obj, path, visit):
    """Rebuild a pytree, calling ``visit(path, leaf)`` on every tensor
    leaf (non-tensor leaves pass through untouched).  Dict keys traverse
    in sorted order so cross-rank collective names rendezvous."""
    if isinstance(obj, dict):
        return {k: _walk(obj[k], f"{path}.{k}", visit)
                for k in sorted(obj, key=str)}
    if isinstance(obj, tuple):
        vals = [_walk(v, f"{path}.{i}", visit) for i, v in enumerate(obj)]
        if hasattr(obj, "_fields"):
            return type(obj)(*vals)
        return tuple(vals)
    if isinstance(obj, list):
        return [_walk(v, f"{path}.{i}", visit) for i, v in enumerate(obj)]
    if obj is None or isinstance(obj, (str, bytes)):
        return obj
    if np.asarray(obj).dtype == object:
        return obj  # not a tensor leaf; nothing to broadcast
    return visit(path, obj)


class ElasticState:
    """Named training-state slots with commit/rollback semantics.

    >>> state = ElasticState(params=params, opt=opt_state, step=0)
    >>> state.step += 1; state.params = new_params
    >>> state.commit()          # durable point: rollback target
    >>> state.restore()         # back to the last commit
    >>> state.sync()            # adopt rank 0's values everywhere

    The constructor takes the initial snapshot, so ``restore()`` is always
    well-defined.  Slots are plain attributes between calls; only the
    names given at construction are tracked.

    Under elastic membership the world ``sync()`` runs in may differ from
    the previous entry's (shrunk to survivors, or re-grown by a rejoined
    replacement); ``last_sync_size`` / ``last_sync_epoch`` record the
    world each sync committed into, so a training loop can detect an
    in-place resize and re-derive anything size-dependent (per-rank
    shards, loss scaling, data partitions).
    """

    def __init__(self, **slots):
        if not slots:
            raise ValueError("ElasticState needs at least one named slot")
        self._keys = sorted(slots)
        for k, v in slots.items():
            setattr(self, k, v)
        self._commit_count = 0
        self._snapshot: dict = {}
        #: World identity of the most recent sync() (None before the
        #: first): the membership a resumed step loop is running under.
        self.last_sync_size: int | None = None
        self.last_sync_epoch: int | None = None
        self.commit()

    @property
    def commit_count(self) -> int:
        """Monotonic count of commits (incl. the constructor's and each
        ``sync()``'s) — :func:`run_elastic` uses it to detect progress
        between failures and reset its retry budget."""
        return self._commit_count

    def commit(self) -> None:
        """Snapshot every slot to host numpy; the new rollback target."""
        self._snapshot = {k: _host_copy(getattr(self, k))
                          for k in self._keys}
        self._commit_count += 1

    def restore(self) -> None:
        """Roll every slot back to the last commit (copies, so later
        mutation cannot corrupt the snapshot)."""
        for k in self._keys:
            setattr(self, k, _host_copy(self._snapshot[k]))

    def sync(self, root_rank: int = 0) -> None:
        """Broadcast every slot from ``root_rank`` and commit the result.

        Collective: all ranks must call it at the same point.  After a
        failure, survivors ``restore()`` then ``sync()`` while a
        relaunched worker syncs its fresh state — everyone leaves with
        rank 0's committed values (including step counters).  Because the
        broadcast spans whatever world the current membership epoch
        committed, this is also what redistributes state across an
        in-place RESIZE: the shrunken (or re-grown) world leaves sync
        with identical state regardless of which ranks survived.
        """
        from horovod_tpu.common.basics import basics

        eng = engine_or_none()
        if eng is not None:
            # Enqueue EVERY leaf broadcast before synchronizing any (the
            # engine's batched idiom, cf. eager.grouped_allreduce): the
            # coordinator negotiates the whole batch in ~one cycle
            # instead of paying one blocking round-trip per leaf.
            handles = []

            def enqueue(path, leaf):
                arr = np.asarray(leaf)
                buf = np.ascontiguousarray(
                    arr.reshape(1) if arr.ndim == 0 else arr).copy()
                handles.append(eng.enqueue_broadcast(
                    buf, root_rank, name=f"elastic.sync.{path}"))
                return leaf

            for k in self._keys:
                _walk(getattr(self, k), k, enqueue)
            # Drain every handle even when one fails (eng.drain — the
            # shared hygiene: a half-drained batch would poison the
            # retry after a mid-sync abort with duplicate-name errors).
            outs, _infos, first_err = eng.drain(handles)
            if first_err is not None:
                raise first_err
            results = iter(outs)

            def adopt(path, leaf):
                out = next(results)
                if np.asarray(leaf).ndim == 0:
                    val = out.reshape(())[()]
                    if isinstance(leaf, bool):
                        return bool(val)
                    if isinstance(leaf, int):
                        return int(val)
                    if isinstance(leaf, float):
                        return float(val)
                    return val
                return out

            for k in self._keys:
                setattr(self, k, _walk(getattr(self, k), k, adopt))
        self.last_sync_size = basics.size() if basics.is_initialized() else 1
        self.last_sync_epoch = basics.epoch()
        self.commit()


def default_local_sgd_steps() -> int:
    """The ``HOROVOD_LOCAL_SGD_STEPS`` env default (H local steps per
    outer sync; 1 = fully synchronous, the pre-local-SGD contract)."""
    raw = os.environ.get("HOROVOD_LOCAL_SGD_STEPS", "")
    try:
        v = int(raw) if raw else 1
    except ValueError:
        v = 1
    return max(1, v)


class LocalSGD:
    """Communication-relaxed periodic sync (the DiLoCo / local-SGD
    pattern): run ``H`` purely LOCAL optimizer steps, then one outer
    allreduce of the model — the delta-average step
    ``anchor + avg(P_r - anchor)`` shipped as each rank's summed-out
    ``P_r`` so reconstruction is ANCHOR-FREE (see ``maybe_sync``) —
    wire traffic drops by ``H``×, and the one sync that remains rides
    the ordinary allreduce path, so it composes unchanged with wire
    compression (``HOROVOD_WIRE_DTYPE``), the shm hierarchy, and
    backup-worker partial commits (divisor-correct averaging by
    participants).

    Usage (the optimizer frontends wire this up from
    ``DistributedOptimizer(local_sgd_steps=H)``)::

        policy = LocalSGD(local_sgd_steps=8)
        policy.begin(params)              # anchor the outer model
        for batch in data:
            params = local_step(params, batch)   # NO gradient allreduce
            params = policy.maybe_sync(params)   # wire sync every H-th

    Epoch stamping (the top-k error-feedback residual rule): the anchor
    is stamped with the membership epoch it was taken under.  An elastic
    resize (abort/shrink/rejoin) bumps the epoch, and the next
    ``maybe_sync`` RE-ANCHORS to the current params instead of
    allreducing a dead incarnation's delta into the new world — after
    the resize's ``ElasticState.sync()`` restored a consistent model,
    local counting restarts cleanly.

    A :class:`~horovod_tpu.runtime.engine.StepSkipped` outer sync (this
    rank left out of a backup-worker partial commit) keeps the local
    params, re-anchors to them, and does NOT count as a sync — and
    because reconstruction is anchor-free, the rank lands exactly on
    the participants' consensus at its NEXT successful sync: the drift
    really is bounded by one outer round, never a frozen offset.

    ``compression=Compression.topk(ratio)`` routes the outer sync
    through the TOP-K SPARSE path instead of the dense allreduce: the
    policy then keeps the anchor VALUES (a host model copy) and ships
    each float leaf's DELTA ``P_r - anchor`` as its k largest-magnitude
    entries, with ITS OWN epoch-stamped error-feedback residuals (keyed
    ``local_sgd.delta.*`` in runtime.sparse — unsent delta mass carries
    into the next outer round, never lost, and an elastic resize resets
    it with the epoch stamp).  Wire bytes drop by ~H/ratio vs per-step
    dense sync combined.  Reconstruction is anchor-BASED in this mode
    (``anchor + avg(topk(delta))``); non-float leaves stay local.
    """

    def __init__(self, local_sgd_steps: int | None = None,
                 compression=None):
        self.steps = int(local_sgd_steps) if local_sgd_steps is not None \
            else default_local_sgd_steps()
        if self.steps < 1:
            self.steps = 1
        self._local_steps = 0
        # Duck-typed (both the jax and torch frontends name their spec
        # class TopKCompressor; importing either would drag a framework
        # into this deliberately framework-free module).
        self._topk = compression if (
            type(compression).__name__ == "TopKCompressor"
            and hasattr(compression, "ratio")) else None
        # The anchor is a cadence/epoch MARKER, not a model copy —
        # reconstruction is anchor-free (each sync averages the ranks'
        # models), so storing the values would pin a full duplicate of
        # the model per training run for nothing.  EXCEPT under top-k:
        # the sparse path ships deltas, so the anchor values are
        # load-bearing there (one host copy, the DiLoCo trade).
        self._anchored = False
        self._anchor_epoch: int | None = None
        self._anchor_values = None
        #: Completed outer syncs (process-local mirror of the engine's
        #: cumulative ``local_sgd_syncs`` counter).
        self.sync_count = 0

    def _epoch(self) -> int:
        from horovod_tpu.common.basics import basics

        if not basics.is_initialized():
            return 0
        eng = engine_or_none()
        return eng.epoch() if eng is not None else 0

    def begin(self, params=None) -> None:
        """Anchor the outer (synchronized) model — call once before the
        first local step.  In dense mode ``params`` is accepted for
        call-site clarity but not stored (reconstruction is
        anchor-free); in top-k mode the anchor VALUES are kept (the
        sparse path ships deltas), and a value-less ``begin()`` defers
        anchoring to the first ``maybe_sync`` that sees the params."""
        self._anchor_epoch = self._epoch()
        self._local_steps = 0
        if self._topk is not None:
            if params is None:
                self._anchored = False
                self._anchor_values = None
                return
            self._anchor_values = _host_copy(params)
        self._anchored = True

    def reset(self) -> None:
        """Drop the anchor (a fresh training run in the same process);
        the next ``maybe_sync`` re-anchors without syncing."""
        self._anchored = False
        self._anchor_epoch = None
        self._anchor_values = None
        self._local_steps = 0

    def maybe_sync(self, params):
        """Count one completed local step; on the ``H``-th, allreduce the
        model delta and return the synced params (otherwise return
        ``params`` unchanged — the SAME object, so callers can detect
        whether a sync happened by identity)."""
        from horovod_tpu.runtime.engine import StepSkipped
        from horovod_tpu.runtime.engine import note_local_sgd_sync

        epoch = self._epoch()
        if not self._anchored or self._anchor_epoch != epoch:
            # First sighting, or the membership epoch moved under us (an
            # elastic resize committed a new world): the pending delta
            # belongs to a dead incarnation — drop it and re-anchor.
            self.begin(params)
            return params
        self._local_steps += 1
        if self._local_steps < self.steps:
            return params

        if self._topk is not None:
            return self._sync_topk(params)

        from horovod_tpu.common.basics import basics

        eng = engine_or_none() if basics.is_initialized() else None
        if eng is None:
            # World of one: the sync is an arithmetic identity, but the
            # cadence (re-anchor + count) still applies so code paths
            # are identical at any scale.
            self.begin(params)
            self.sync_count += 1
            note_local_sgd_sync()
            return params

        # One outer allreduce per leaf, batched: enqueue everything
        # before draining anything (the engine fuses the burst),
        # averaged divisor-correctly by participants.  The wire carries
        # each rank's CURRENT model leaf — i.e. anchor + delta summed on
        # the sender — which over an agreed anchor is arithmetically the
        # delta-average outer step (avg(P_r) = S + avg(P_r - S)), but is
        # ANCHOR-FREE on reconstruction: a rank whose anchor was
        # perturbed (a skipped outer sync, an elastic re-anchor) lands
        # exactly on the participants' consensus at its next successful
        # sync instead of freezing a permanent offset.
        paths, sends = [], []

        def collect(path, leaf):
            arr = np.asarray(leaf)
            paths.append(path)
            sends.append(np.ascontiguousarray(arr))
            return leaf

        _walk(params, "p", collect)

        handles = [
            eng.enqueue_allreduce(
                np.ascontiguousarray(d.reshape(1) if d.ndim == 0 else d),
                name=f"local_sgd.sync.{path}")
            for path, d in zip(paths, sends)
        ]
        outs, infos, first_err = eng.drain(handles)
        if first_err is not None:
            if isinstance(first_err, StepSkipped):
                # Left out of the outer sync (backup workers): keep the
                # local model, restart local counting from it.  The next
                # SUCCESSFUL sync heals this completely — reconstruction
                # averages the participants' models, anchor-free.
                self.begin(params)
                return params
            raise first_err

        avg = iter([
            eng._apply_average(o, i.get("participants") or None)
            for o, i in zip(outs, infos)
        ])

        def adopt(path, leaf):
            arr = np.asarray(leaf)
            new = next(avg).reshape(arr.shape).astype(arr.dtype)
            if arr.ndim == 0:
                val = new.reshape(())[()]
                if isinstance(leaf, bool):
                    return bool(val)
                if isinstance(leaf, int):
                    return int(val)
                if isinstance(leaf, float):
                    return float(val)
                return val
            return new

        synced = _walk(params, "p", adopt)
        self.begin(synced)
        self.sync_count += 1
        note_local_sgd_sync()
        return synced

    def _sync_topk(self, params):
        """Outer sync over the top-k sparse path: per float leaf, ship
        top-k of the delta ``P_r - anchor`` (error-feedback residuals
        keyed ``local_sgd.delta.<path>``, epoch-stamped by
        runtime.sparse) and reconstruct ``anchor + avg_delta``.
        Sequential per leaf (two allgathers each) — top-k is the opt-in
        bandwidth-starved regime where that trade is the point."""
        from horovod_tpu.runtime.engine import note_local_sgd_sync
        from horovod_tpu.runtime.sparse import sparse_allreduce_topk

        anchors: dict = {}

        def grab(path, leaf):
            anchors[path] = np.asarray(leaf)
            return leaf

        _walk(self._anchor_values, "p", grab)

        def sync_leaf(path, leaf):
            arr = np.asarray(leaf)
            anchor = anchors.get(path)
            if (not np.issubdtype(arr.dtype, np.floating)
                    or anchor is None or anchor.shape != arr.shape):
                # Non-float slots (and structure drift, which the next
                # re-anchor repairs) stay local: a sparse delta of a
                # step counter is meaningless.
                return leaf
            delta = arr.astype(np.float32) - anchor.astype(np.float32)
            avg = sparse_allreduce_topk(
                delta, name=f"local_sgd.delta.{path}",
                ratio=self._topk.ratio,
                error_feedback=getattr(self._topk, "error_feedback",
                                       True),
                average=True)
            return (anchor.astype(np.float32) + avg).astype(arr.dtype)

        synced = _walk(params, "p", sync_leaf)
        self.begin(synced)
        self.sync_count += 1
        note_local_sgd_sync()
        return synced
