"""Commit/restore/sync of training state for elastic recovery.

Role parity with Elastic Horovod's ``hvd.elastic.State`` (commit /
restore / sync): the state object owns named slots (params, optimizer
state, step counter, ...), snapshots them to host numpy on ``commit()``,
rolls back on ``restore()``, and ``sync()`` broadcasts the current values
from a root so every rank — including a freshly relaunched worker —
proceeds from identical state.

Slots hold pytrees: arbitrarily nested dict / list / tuple (incl.
namedtuples, so raw optax states work) with array-like or scalar leaves.
Leaves are traversed in sorted-key order so cross-rank collective names
rendezvous deterministically.
"""

from __future__ import annotations

import numpy as np

from horovod_tpu.runtime import engine_or_none

__all__ = ["ElasticState"]


def _host_copy(obj):
    """Deep copy a pytree with every array leaf as a host numpy copy."""
    if isinstance(obj, dict):
        return {k: _host_copy(v) for k, v in obj.items()}
    if isinstance(obj, tuple):
        vals = [_host_copy(v) for v in obj]
        if hasattr(obj, "_fields"):  # namedtuple (e.g. optax state)
            return type(obj)(*vals)
        return tuple(vals)
    if isinstance(obj, list):
        return [_host_copy(v) for v in obj]
    if obj is None or isinstance(obj, (bool, int, float, complex, str,
                                       bytes)):
        return obj
    # Array-like (numpy, jax, torch-on-cpu via __array__): materialize on
    # host, detached from any device buffer the engine might clobber.
    return np.array(np.asarray(obj), copy=True)


def _walk(obj, path, visit):
    """Rebuild a pytree, calling ``visit(path, leaf)`` on every tensor
    leaf (non-tensor leaves pass through untouched).  Dict keys traverse
    in sorted order so cross-rank collective names rendezvous."""
    if isinstance(obj, dict):
        return {k: _walk(obj[k], f"{path}.{k}", visit)
                for k in sorted(obj, key=str)}
    if isinstance(obj, tuple):
        vals = [_walk(v, f"{path}.{i}", visit) for i, v in enumerate(obj)]
        if hasattr(obj, "_fields"):
            return type(obj)(*vals)
        return tuple(vals)
    if isinstance(obj, list):
        return [_walk(v, f"{path}.{i}", visit) for i, v in enumerate(obj)]
    if obj is None or isinstance(obj, (str, bytes)):
        return obj
    if np.asarray(obj).dtype == object:
        return obj  # not a tensor leaf; nothing to broadcast
    return visit(path, obj)


class ElasticState:
    """Named training-state slots with commit/rollback semantics.

    >>> state = ElasticState(params=params, opt=opt_state, step=0)
    >>> state.step += 1; state.params = new_params
    >>> state.commit()          # durable point: rollback target
    >>> state.restore()         # back to the last commit
    >>> state.sync()            # adopt rank 0's values everywhere

    The constructor takes the initial snapshot, so ``restore()`` is always
    well-defined.  Slots are plain attributes between calls; only the
    names given at construction are tracked.

    Under elastic membership the world ``sync()`` runs in may differ from
    the previous entry's (shrunk to survivors, or re-grown by a rejoined
    replacement); ``last_sync_size`` / ``last_sync_epoch`` record the
    world each sync committed into, so a training loop can detect an
    in-place resize and re-derive anything size-dependent (per-rank
    shards, loss scaling, data partitions).
    """

    def __init__(self, **slots):
        if not slots:
            raise ValueError("ElasticState needs at least one named slot")
        self._keys = sorted(slots)
        for k, v in slots.items():
            setattr(self, k, v)
        self._commit_count = 0
        self._snapshot: dict = {}
        #: World identity of the most recent sync() (None before the
        #: first): the membership a resumed step loop is running under.
        self.last_sync_size: int | None = None
        self.last_sync_epoch: int | None = None
        self.commit()

    @property
    def commit_count(self) -> int:
        """Monotonic count of commits (incl. the constructor's and each
        ``sync()``'s) — :func:`run_elastic` uses it to detect progress
        between failures and reset its retry budget."""
        return self._commit_count

    def commit(self) -> None:
        """Snapshot every slot to host numpy; the new rollback target."""
        self._snapshot = {k: _host_copy(getattr(self, k))
                          for k in self._keys}
        self._commit_count += 1

    def restore(self) -> None:
        """Roll every slot back to the last commit (copies, so later
        mutation cannot corrupt the snapshot)."""
        for k in self._keys:
            setattr(self, k, _host_copy(self._snapshot[k]))

    def sync(self, root_rank: int = 0) -> None:
        """Broadcast every slot from ``root_rank`` and commit the result.

        Collective: all ranks must call it at the same point.  After a
        failure, survivors ``restore()`` then ``sync()`` while a
        relaunched worker syncs its fresh state — everyone leaves with
        rank 0's committed values (including step counters).  Because the
        broadcast spans whatever world the current membership epoch
        committed, this is also what redistributes state across an
        in-place RESIZE: the shrunken (or re-grown) world leaves sync
        with identical state regardless of which ranks survived.
        """
        from horovod_tpu.common.basics import basics

        eng = engine_or_none()
        if eng is not None:
            # Enqueue EVERY leaf broadcast before synchronizing any (the
            # engine's batched idiom, cf. eager.grouped_allreduce): the
            # coordinator negotiates the whole batch in ~one cycle
            # instead of paying one blocking round-trip per leaf.
            handles = []

            def enqueue(path, leaf):
                arr = np.asarray(leaf)
                buf = np.ascontiguousarray(
                    arr.reshape(1) if arr.ndim == 0 else arr).copy()
                handles.append(eng.enqueue_broadcast(
                    buf, root_rank, name=f"elastic.sync.{path}"))
                return leaf

            for k in self._keys:
                _walk(getattr(self, k), k, enqueue)
            # Drain every handle even when one fails (same hygiene as
            # grouped_allreduce: a half-drained batch would poison the
            # retry after a mid-sync abort with duplicate-name errors).
            outs, first_err = [], None
            for h in handles:
                try:
                    outs.append(eng.synchronize(h))
                except Exception as e:  # noqa: BLE001 — re-raised below
                    if first_err is None:
                        first_err = e
                    outs.append(None)
            if first_err is not None:
                raise first_err
            results = iter(outs)

            def adopt(path, leaf):
                out = next(results)
                if np.asarray(leaf).ndim == 0:
                    val = out.reshape(())[()]
                    if isinstance(leaf, bool):
                        return bool(val)
                    if isinstance(leaf, int):
                        return int(val)
                    if isinstance(leaf, float):
                        return float(val)
                    return val
                return out

            for k in self._keys:
                setattr(self, k, _walk(getattr(self, k), k, adopt))
        self.last_sync_size = basics.size() if basics.is_initialized() else 1
        self.last_sync_epoch = basics.epoch()
        self.commit()
