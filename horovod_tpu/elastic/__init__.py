"""Elastic fault-tolerant training (TPU-native Elastic Horovod analogue).

The engine's failure detector turns a dead, hung, or disconnected rank
into a prompt :class:`~horovod_tpu.runtime.engine.HorovodInternalError`
on every surviving rank (naming the culprit).  This package supplies the
recovery half:

* :class:`ElasticState` — commit/restore snapshots of params, optimizer
  state, and step counters as host-side numpy copies, plus ``sync()`` to
  broadcast the committed state from rank 0 so a relaunched worker joins
  at the survivors' rollback point.
* :func:`run_elastic` — a driver that runs ``train_fn(state)``, and on a
  collective failure re-initializes the runtime, rolls back to the last
  commit, and retries with capped exponential backoff
  (``HOROVOD_ELASTIC_MAX_RETRIES`` / ``HOROVOD_ELASTIC_BACKOFF_SEC``).

Deliberately jax-free (numpy + the native engine only) so the torch
frontend and multi-process tests can use it standalone; jax array leaves
are accepted and come back as numpy (jax ops coerce them transparently).

See docs/elastic.md for the failure model and semantics.
"""

from horovod_tpu.elastic.driver import run_elastic
from horovod_tpu.elastic.state import (ElasticState, LocalSGD,
                                       default_local_sgd_steps)

__all__ = ["ElasticState", "LocalSGD", "default_local_sgd_steps",
           "run_elastic"]
