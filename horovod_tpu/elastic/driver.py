"""The elastic retry driver: catch → re-init → rollback → resume.

``run_elastic(train_fn, state)`` is the TPU-native analogue of Elastic
Horovod's ``@hvd.elastic.run`` wrapper: the training function runs until
it either finishes or a rank failure surfaces as
:class:`HorovodInternalError`; on failure the driver tears the engine
down, waits out a capped exponential backoff, re-rendezvouses (the
launcher's ``--restart-on-failure`` supervisor replaces dead workers in
the meantime), rolls the state back to its last commit, and re-enters
``train_fn``.  ``state.sync()`` at every (re-)entry makes rank 0's
committed state authoritative, so relaunched workers join at the
survivors' rollback point instead of step 0.
"""

from __future__ import annotations

import os
import random
import sys
import time
from typing import Callable, Optional

from horovod_tpu.common.basics import basics
from horovod_tpu.elastic.state import ElasticState
from horovod_tpu.runtime.engine import HorovodInternalError

__all__ = ["run_elastic"]

#: Ceiling on any single backoff sleep, however many doublings happened.
_BACKOFF_CAP_SEC = 30.0


def _env_num(name: str, default, cast):
    value = os.environ.get(name)
    if value is None or value == "":
        return default
    return cast(value)


def _jittered(delay: float, attempt: int) -> float:
    """±25% seeded jitter on a backoff delay.

    A deterministic exponential backoff makes every survivor sleep the
    IDENTICAL delay after a collective failure, so the whole world
    reconnects to the coordinator in the same instant (thundering-herd
    rendezvous).  The jitter is seeded from (persistent worker id,
    attempt): decorrelated across ranks, yet reproducible per process so
    failures replay identically under test.
    """
    rng = random.Random(f"{os.environ.get('HOROVOD_RANK', '0')}:{attempt}")
    return delay * (0.75 + 0.5 * rng.random())


def run_elastic(train_fn: Callable[[ElasticState], object],
                state: ElasticState, *,
                max_retries: Optional[int] = None,
                backoff_sec: Optional[float] = None):
    """Run ``train_fn(state)`` with checkpoint-rollback recovery.

    ``train_fn`` should loop on ``state`` (e.g. ``while state.step < N``),
    call ``state.commit()`` at durable points, and simply let
    ``HorovodInternalError`` propagate — the driver owns recovery.  Its
    return value is returned when it completes.

    Retries are bounded by ``max_retries`` (default
    ``HOROVOD_ELASTIC_MAX_RETRIES``, 3); the budget RESETS whenever a
    commit landed since the previous failure, so a long run survives many
    spaced-out failures while a crash loop still terminates.  Backoff
    starts at ``backoff_sec`` (default ``HOROVOD_ELASTIC_BACKOFF_SEC``,
    1.0) and doubles per consecutive failure, capped at 30 s, with ±25%
    seeded jitter so survivors don't hammer the coordinator in lockstep.
    """
    if max_retries is None:
        max_retries = _env_num("HOROVOD_ELASTIC_MAX_RETRIES", 3, int)
    if backoff_sec is None:
        backoff_sec = _env_num("HOROVOD_ELASTIC_BACKOFF_SEC", 1.0, float)

    retries = 0
    while True:
        commits_at_entry = None
        try:
            if not basics.is_initialized():
                basics.init()
                if retries > 0:
                    # Under elastic membership (HOROVOD_ELASTIC=1) the
                    # re-init may have committed a RESIZED world — shrunk
                    # to the survivors, or re-grown by a rejoined
                    # replacement — so train_fn must re-read rank/size.
                    print(
                        "horovod_tpu elastic: re-entered the world at "
                        f"epoch={basics.epoch()} rank={basics.rank()} "
                        f"size={basics.size()}",
                        file=sys.stderr, flush=True)
            ckpt_dir = os.environ.get("HOROVOD_CHECKPOINT_DIR",
                                      "").strip()
            if ckpt_dir:
                # Disk beats memory only when rank 0 (the sync
                # authority) lost progress — a full-fleet relaunch, or
                # rank 0 itself died.  Collective: every rank takes the
                # same branch (checkpoint/elastic.py).
                from horovod_tpu.checkpoint import maybe_restore

                restored = maybe_restore(state, ckpt_dir)
                if restored is not None:
                    print(
                        "horovod_tpu elastic: restored from checkpoint "
                        f"step {restored} ({ckpt_dir})",
                        file=sys.stderr, flush=True)
            state.sync()
            commits_at_entry = state.commit_count
            return train_fn(state)
        except HorovodInternalError as e:
            if commits_at_entry is not None \
                    and state.commit_count > commits_at_entry:
                retries = 0  # made durable progress since the last failure
            retries += 1
            if retries > max_retries:
                print(
                    "horovod_tpu elastic: giving up after "
                    f"{max_retries} consecutive retries: {e}",
                    file=sys.stderr, flush=True)
                raise
            delay = _jittered(
                min(backoff_sec * (2 ** (retries - 1)), _BACKOFF_CAP_SEC),
                retries)
            print(
                f"horovod_tpu elastic: collective failure ({e}); "
                f"rolling back to the last commit and retrying in "
                f"{delay:.1f}s (attempt {retries}/{max_retries})",
                file=sys.stderr, flush=True)
            try:
                basics.shutdown()
            except Exception:  # noqa: BLE001 — teardown is best-effort
                pass
            state.restore()
            time.sleep(delay)
