#include "timeline.h"

#include <cstdarg>
#include <cstdio>

namespace hvd {

void Timeline::Initialize(const std::string& path) {
  std::lock_guard<std::recursive_mutex> lk(mu_);
  if (file_ != nullptr) {
    // Re-Init in the same process (elastic recovery, autotune's
    // startup-probe churn).  Same committed path → same rank: keep the
    // window open and accumulating (a probe restart must not discard
    // the run's events) — but restart the per-name FLOW counters:
    // every writing rank re-initializes at the same rendezvous, and
    // the membership epoch inside the flow id separates incarnations,
    // so cross-rank flow ids stay joined after a resize or a worker
    // relaunch (a surviving sender continuing from its old counts
    // against a relaunched receiver's zeros would desync forever).
    flow_send_.clear();
    flow_recv_.clear();
    if (path == path_) return;
    // Path changed → an elastic re-rank moved this writer's label:
    // terminate the old-rank file as valid JSON and start fresh at the
    // new name, or every post-resize event would be misattributed to
    // the dead incarnation's rank (and aligned with its stale offset).
    Out("{\"name\": \"horovod_end\", \"ph\": \"M\", \"pid\": 0}\n]\n");
    std::fflush(file_);
    std::fclose(file_);
    file_ = nullptr;
    tensor_pids_.clear();
    next_pid_ = 0;
    tune_span_open_ = false;
  }
  file_ = std::fopen(path.c_str(), "w");
  if (file_ == nullptr) {
    std::fprintf(stderr, "horovod_tpu: cannot open timeline file %s\n",
                 path.c_str());
    return;
  }
  path_ = path;
  written_ = 0;
  Out("[\n");
  start_ = std::chrono::steady_clock::now();
  last_flush_ = start_;
}

Timeline::~Timeline() {
  std::lock_guard<std::recursive_mutex> lk(mu_);
  if (file_ != nullptr) {
    // Terminate as valid JSON (the events all carry trailing commas, so
    // close with a final metadata event + bracket).  Chrome tracing
    // tolerates the unterminated form too — this is for `timeline
    // merge` and any strict JSON consumer.
    Out("{\"name\": \"horovod_end\", \"ph\": \"M\", \"pid\": 0}\n]\n");
    std::fflush(file_);
    std::fclose(file_);
    file_ = nullptr;
  }
}

void Timeline::Out(const char* fmt, ...) {
  va_list ap;
  va_start(ap, fmt);
  int n = std::vfprintf(file_, fmt, ap);
  va_end(ap);
  if (n > 0) written_ += n;
}

int64_t Timeline::NowUs() const {
  return std::chrono::duration_cast<std::chrono::microseconds>(
             std::chrono::steady_clock::now() - start_)
      .count();
}

void Timeline::SetMeta(int rank, int64_t epoch, int64_t clock_offset_ns) {
  std::lock_guard<std::recursive_mutex> lk(mu_);
  meta_rank_ = rank;
  meta_epoch_ = epoch;
  meta_offset_ns_ = clock_offset_ns;
  meta_set_ = true;
  if (file_ != nullptr) WriteMetaHeader();
}

void Timeline::WriteMetaHeader() {
  // mono_base_us: the trace's ts=0 instant on this process's monotonic
  // clock.  An event at trace time ts sits at rank-0 monotonic time
  // (ts + mono_base_us + clock_offset_us) — the merge tool's whole
  // alignment formula.
  const int64_t base_us =
      std::chrono::duration_cast<std::chrono::microseconds>(
          start_.time_since_epoch())
          .count();
  Out("{\"name\": \"horovod_meta\", \"ph\": \"M\", \"pid\": 0, "
      "\"args\": {\"rank\": %d, \"epoch\": %lld, \"mono_base_us\": %lld, "
      "\"clock_offset_us\": %lld}},\n",
      meta_rank_, static_cast<long long>(meta_epoch_),
      static_cast<long long>(base_us),
      static_cast<long long>(meta_offset_ns_ / 1000));
}

void Timeline::MaybeRotate() {
  if (max_bytes_ <= 0 || written_ <= max_bytes_ || path_.empty()) return;
  // Terminate the full file as valid JSON, keep it as "<path>.old"
  // (newest-but-one window), and continue fresh at the configured path —
  // the newest events always live in the file the operator configured.
  Out("{\"name\": \"horovod_rotated\", \"ph\": \"M\", \"pid\": 0}\n]\n");
  std::fflush(file_);
  std::fclose(file_);
  std::string old = path_ + ".old";
  std::rename(path_.c_str(), old.c_str());
  file_ = std::fopen(path_.c_str(), "w");
  if (file_ == nullptr) return;
  written_ = 0;
  Out("[\n");
  if (meta_set_) WriteMetaHeader();
  // Re-emit pid metadata so the fresh file is self-contained.
  for (const auto& kv : tensor_pids_) {
    Out("{\"name\": \"process_name\", \"ph\": \"M\", \"pid\": %d, "
        "\"args\": {\"name\": \"%s\"}},\n",
        kv.second, kv.first.c_str());
    Out("{\"name\": \"process_sort_index\", \"ph\": \"M\", \"pid\": %d, "
        "\"args\": {\"sort_index\": %d}},\n",
        kv.second, kv.second);
  }
}

void Timeline::Flush() {
  std::lock_guard<std::recursive_mutex> lk(mu_);
  if (file_ != nullptr) std::fflush(file_);
}

int Timeline::TensorPid(const std::string& name) {
  auto it = tensor_pids_.find(name);
  if (it != tensor_pids_.end()) return it->second;
  int pid = next_pid_++;
  tensor_pids_[name] = pid;
  // Metadata event naming the "process" after the tensor (reference
  // timeline.cc:51-68).
  Out("{\"name\": \"process_name\", \"ph\": \"M\", \"pid\": %d, "
      "\"args\": {\"name\": \"%s\"}},\n",
      pid, name.c_str());
  Out("{\"name\": \"process_sort_index\", \"ph\": \"M\", "
      "\"pid\": %d, \"args\": {\"sort_index\": %d}},\n",
      pid, pid);
  return pid;
}

void Timeline::WriteEvent(int pid, char phase, const std::string& category,
                          const std::string& op_name, int tid) {
  Out("{\"ph\": \"%c\", \"ts\": %lld, \"pid\": %d, \"tid\": %d", phase,
      static_cast<long long>(NowUs()), pid, tid);
  if (!category.empty()) {
    Out(", \"cat\": \"%s\"", category.c_str());
  }
  if (!op_name.empty()) {
    Out(", \"name\": \"%s\"", op_name.c_str());
  }
  Out("},\n");
  MaybeRotate();
  FlushIfDue();
}

void Timeline::FlushIfDue() {
  auto now = std::chrono::steady_clock::now();
  if (now - last_flush_ > std::chrono::seconds(1)) {
    std::fflush(file_);
    last_flush_ = now;
  }
}

void Timeline::NegotiateStart(const std::string& name) {
  std::lock_guard<std::recursive_mutex> lk(mu_);
  if (file_ == nullptr) return;
  WriteEvent(TensorPid(name), 'B', "NEGOTIATE", "NEGOTIATE");
}

void Timeline::NegotiateRankReady(const std::string& name, int rank) {
  std::lock_guard<std::recursive_mutex> lk(mu_);
  if (file_ == nullptr) return;
  WriteEvent(TensorPid(name), 'X', "NEGOTIATE",
             "rank_" + std::to_string(rank) + "_ready");
}

void Timeline::NegotiateEnd(const std::string& name) {
  std::lock_guard<std::recursive_mutex> lk(mu_);
  if (file_ == nullptr) return;
  WriteEvent(TensorPid(name), 'E', "NEGOTIATE");
}

void Timeline::NegotiateCached(const std::string& name) {
  std::lock_guard<std::recursive_mutex> lk(mu_);
  if (file_ == nullptr) return;
  WriteEvent(TensorPid(name), 'X', "NEGOTIATE", "NEGOTIATE_CACHED");
}

void Timeline::FlowSend(const std::string& name, int64_t epoch) {
  std::lock_guard<std::recursive_mutex> lk(mu_);
  if (file_ == nullptr) return;
  const int64_t n = flow_send_[name]++;
  Out("{\"ph\": \"s\", \"ts\": %lld, \"pid\": %d, \"tid\": 0, "
      "\"cat\": \"FLOW\", \"name\": \"negotiate\", "
      "\"id\": \"%s#%lld#%lld\"},\n",
      static_cast<long long>(NowUs()), TensorPid(name), name.c_str(),
      static_cast<long long>(epoch), static_cast<long long>(n));
  MaybeRotate();
  FlushIfDue();
}

void Timeline::FlowRecv(const std::string& name, int64_t epoch) {
  std::lock_guard<std::recursive_mutex> lk(mu_);
  if (file_ == nullptr) return;
  const int64_t n = flow_recv_[name]++;
  Out("{\"ph\": \"f\", \"bp\": \"e\", \"ts\": %lld, \"pid\": %d, "
      "\"tid\": 0, \"cat\": \"FLOW\", \"name\": \"negotiate\", "
      "\"id\": \"%s#%lld#%lld\"},\n",
      static_cast<long long>(NowUs()), TensorPid(name), name.c_str(),
      static_cast<long long>(epoch), static_cast<long long>(n));
  MaybeRotate();
  FlushIfDue();
}

void Timeline::Start(const std::string& name) {
  std::lock_guard<std::recursive_mutex> lk(mu_);
  if (file_ == nullptr) return;
  WriteEvent(TensorPid(name), 'B', "OP", name);
}

void Timeline::ActivityStart(const std::string& name,
                             const std::string& activity) {
  std::lock_guard<std::recursive_mutex> lk(mu_);
  if (file_ == nullptr) return;
  WriteEvent(TensorPid(name), 'B', "ACTIVITY", activity);
}

void Timeline::ActivityEnd(const std::string& name) {
  std::lock_guard<std::recursive_mutex> lk(mu_);
  if (file_ == nullptr) return;
  WriteEvent(TensorPid(name), 'E', "ACTIVITY");
}

void Timeline::ActivityStartCh(const std::string& name,
                               const std::string& activity, int tid) {
  std::lock_guard<std::recursive_mutex> lk(mu_);
  if (file_ == nullptr) return;
  WriteEvent(TensorPid(name), 'B', "ACTIVITY", activity, tid);
}

void Timeline::ActivityEndCh(const std::string& name, int tid) {
  std::lock_guard<std::recursive_mutex> lk(mu_);
  if (file_ == nullptr) return;
  WriteEvent(TensorPid(name), 'E', "ACTIVITY", "", tid);
}

void Timeline::Algo(const std::string& name, const char* algo) {
  std::lock_guard<std::recursive_mutex> lk(mu_);
  if (file_ == nullptr) return;
  WriteEvent(TensorPid(name), 'X', "ACTIVITY", algo);
}

void Timeline::PartialCommit(const std::string& name,
                             const std::string& skipped) {
  std::lock_guard<std::recursive_mutex> lk(mu_);
  if (file_ == nullptr) return;
  WriteEvent(TensorPid(name), 'X', "ACTIVITY",
             "PARTIAL_COMMIT(skipped=" + skipped + ")");
}

void Timeline::TuneTrial(const std::string& config, bool commit) {
  std::lock_guard<std::recursive_mutex> lk(mu_);
  if (file_ == nullptr) return;
  int pid = TensorPid("autotune");
  if (tune_span_open_) {
    WriteEvent(pid, 'E', "AUTOTUNE", "", 1);
    tune_span_open_ = false;
  }
  if (commit) {
    WriteEvent(pid, 'X', "AUTOTUNE", "TUNE_COMMIT(" + config + ")", 1);
    return;
  }
  WriteEvent(pid, 'X', "AUTOTUNE", "TUNE_TRIAL(" + config + ")", 1);
  // The scoring-window span: open until the next trial/commit applies.
  WriteEvent(pid, 'B', "AUTOTUNE", "TUNE_TRIAL(" + config + ")", 1);
  tune_span_open_ = true;
}

void Timeline::End(const std::string& name, DataType dtype,
                   const std::string& shape) {
  std::lock_guard<std::recursive_mutex> lk(mu_);
  if (file_ == nullptr) return;
  int pid = TensorPid(name);
  Out("{\"ph\": \"E\", \"ts\": %lld, \"pid\": %d, \"args\": "
      "{\"dtype\": \"%s\", \"shape\": \"%s\"}},\n",
      static_cast<long long>(NowUs()), pid, DataTypeName(dtype),
      shape.c_str());
  MaybeRotate();
  FlushIfDue();
}

}  // namespace hvd
