#include "timeline.h"

namespace hvd {

void Timeline::Initialize(const std::string& path) {
  std::lock_guard<std::recursive_mutex> lk(mu_);
  if (file_ != nullptr) return;
  file_ = std::fopen(path.c_str(), "w");
  if (file_ == nullptr) {
    std::fprintf(stderr, "horovod_tpu: cannot open timeline file %s\n",
                 path.c_str());
    return;
  }
  std::fputs("[\n", file_);
  start_ = std::chrono::steady_clock::now();
  last_flush_ = start_;
}

Timeline::~Timeline() {
  std::lock_guard<std::recursive_mutex> lk(mu_);
  if (file_ != nullptr) {
    std::fflush(file_);
    std::fclose(file_);
    file_ = nullptr;
  }
}

int64_t Timeline::NowUs() const {
  return std::chrono::duration_cast<std::chrono::microseconds>(
             std::chrono::steady_clock::now() - start_)
      .count();
}

int Timeline::TensorPid(const std::string& name) {
  auto it = tensor_pids_.find(name);
  if (it != tensor_pids_.end()) return it->second;
  int pid = next_pid_++;
  tensor_pids_[name] = pid;
  // Metadata event naming the "process" after the tensor (reference
  // timeline.cc:51-68).
  std::fprintf(file_,
               "{\"name\": \"process_name\", \"ph\": \"M\", \"pid\": %d, "
               "\"args\": {\"name\": \"%s\"}},\n",
               pid, name.c_str());
  std::fprintf(file_,
               "{\"name\": \"process_sort_index\", \"ph\": \"M\", "
               "\"pid\": %d, \"args\": {\"sort_index\": %d}},\n",
               pid, pid);
  return pid;
}

void Timeline::WriteEvent(int pid, char phase, const std::string& category,
                          const std::string& op_name, int tid) {
  std::fprintf(file_, "{\"ph\": \"%c\", \"ts\": %lld, \"pid\": %d, "
               "\"tid\": %d",
               phase, static_cast<long long>(NowUs()), pid, tid);
  if (!category.empty()) {
    std::fprintf(file_, ", \"cat\": \"%s\"", category.c_str());
  }
  if (!op_name.empty()) {
    std::fprintf(file_, ", \"name\": \"%s\"", op_name.c_str());
  }
  std::fputs("},\n", file_);
  FlushIfDue();
}

void Timeline::FlushIfDue() {
  auto now = std::chrono::steady_clock::now();
  if (now - last_flush_ > std::chrono::seconds(1)) {
    std::fflush(file_);
    last_flush_ = now;
  }
}

void Timeline::NegotiateStart(const std::string& name) {
  std::lock_guard<std::recursive_mutex> lk(mu_);
  if (file_ == nullptr) return;
  WriteEvent(TensorPid(name), 'B', "NEGOTIATE", "NEGOTIATE");
}

void Timeline::NegotiateRankReady(const std::string& name, int rank) {
  std::lock_guard<std::recursive_mutex> lk(mu_);
  if (file_ == nullptr) return;
  WriteEvent(TensorPid(name), 'X', "NEGOTIATE",
             "rank_" + std::to_string(rank) + "_ready");
}

void Timeline::NegotiateEnd(const std::string& name) {
  std::lock_guard<std::recursive_mutex> lk(mu_);
  if (file_ == nullptr) return;
  WriteEvent(TensorPid(name), 'E', "NEGOTIATE");
}

void Timeline::NegotiateCached(const std::string& name) {
  std::lock_guard<std::recursive_mutex> lk(mu_);
  if (file_ == nullptr) return;
  WriteEvent(TensorPid(name), 'X', "NEGOTIATE", "NEGOTIATE_CACHED");
}

void Timeline::Start(const std::string& name) {
  std::lock_guard<std::recursive_mutex> lk(mu_);
  if (file_ == nullptr) return;
  WriteEvent(TensorPid(name), 'B', "OP", name);
}

void Timeline::ActivityStart(const std::string& name,
                             const std::string& activity) {
  std::lock_guard<std::recursive_mutex> lk(mu_);
  if (file_ == nullptr) return;
  WriteEvent(TensorPid(name), 'B', "ACTIVITY", activity);
}

void Timeline::ActivityEnd(const std::string& name) {
  std::lock_guard<std::recursive_mutex> lk(mu_);
  if (file_ == nullptr) return;
  WriteEvent(TensorPid(name), 'E', "ACTIVITY");
}

void Timeline::ActivityStartCh(const std::string& name,
                               const std::string& activity, int tid) {
  std::lock_guard<std::recursive_mutex> lk(mu_);
  if (file_ == nullptr) return;
  WriteEvent(TensorPid(name), 'B', "ACTIVITY", activity, tid);
}

void Timeline::ActivityEndCh(const std::string& name, int tid) {
  std::lock_guard<std::recursive_mutex> lk(mu_);
  if (file_ == nullptr) return;
  WriteEvent(TensorPid(name), 'E', "ACTIVITY", "", tid);
}

void Timeline::Algo(const std::string& name, const char* algo) {
  std::lock_guard<std::recursive_mutex> lk(mu_);
  if (file_ == nullptr) return;
  WriteEvent(TensorPid(name), 'X', "ACTIVITY", algo);
}

void Timeline::PartialCommit(const std::string& name,
                             const std::string& skipped) {
  std::lock_guard<std::recursive_mutex> lk(mu_);
  if (file_ == nullptr) return;
  WriteEvent(TensorPid(name), 'X', "ACTIVITY",
             "PARTIAL_COMMIT(skipped=" + skipped + ")");
}

void Timeline::TuneTrial(const std::string& config, bool commit) {
  std::lock_guard<std::recursive_mutex> lk(mu_);
  if (file_ == nullptr) return;
  int pid = TensorPid("autotune");
  if (tune_span_open_) {
    WriteEvent(pid, 'E', "AUTOTUNE", "", 1);
    tune_span_open_ = false;
  }
  if (commit) {
    WriteEvent(pid, 'X', "AUTOTUNE", "TUNE_COMMIT(" + config + ")", 1);
    return;
  }
  WriteEvent(pid, 'X', "AUTOTUNE", "TUNE_TRIAL(" + config + ")", 1);
  // The scoring-window span: open until the next trial/commit applies.
  WriteEvent(pid, 'B', "AUTOTUNE", "TUNE_TRIAL(" + config + ")", 1);
  tune_span_open_ = true;
}

void Timeline::End(const std::string& name, DataType dtype,
                   const std::string& shape) {
  std::lock_guard<std::recursive_mutex> lk(mu_);
  if (file_ == nullptr) return;
  int pid = TensorPid(name);
  std::fprintf(file_,
               "{\"ph\": \"E\", \"ts\": %lld, \"pid\": %d, \"args\": "
               "{\"dtype\": \"%s\", \"shape\": \"%s\"}},\n",
               static_cast<long long>(NowUs()), pid, DataTypeName(dtype),
               shape.c_str());
  FlushIfDue();
}

}  // namespace hvd
