#include "message.h"

#include <algorithm>

namespace hvd {

// Per-cycle control frames are varint-coded end to end (see Writer::vu):
// a steady-state negotiation frame is a handful of one-byte fields, and
// the worst offenders of the fixed-width format — 8-byte epochs, 4-byte
// counts, 8-byte shape dims — shrink to their value's natural size.

static void SerializeRequest(const Request& q, Writer* w) {
  w->vu(static_cast<uint64_t>(q.request_rank));
  w->u8(static_cast<uint8_t>(q.type));
  w->u8(static_cast<uint8_t>(q.dtype));
  w->str(q.tensor_name);
  w->vi(q.root_rank);
  w->u8(static_cast<uint8_t>(q.red_op));
  w->u8(q.probe ? 1 : 0);
  w->u8(static_cast<uint8_t>(q.wire_dtype));
  w->u8(q.wire_default ? 1 : 0);
  w->vu(q.shape.size());
  for (auto d : q.shape) w->vi(d);
  w->vu(q.splits.size());
  for (auto s : q.splits) w->vi(s);
}

static bool ParseRequest(Reader* r, Request* q) {
  q->request_rank = static_cast<int32_t>(r->vu());
  q->type = static_cast<RequestType>(r->u8());
  q->dtype = static_cast<DataType>(r->u8());
  q->tensor_name = r->str();
  q->root_rank = static_cast<int32_t>(r->vi());
  q->red_op = static_cast<ReduceOp>(r->u8());
  q->probe = r->u8() != 0;
  q->wire_dtype = static_cast<WireDtype>(r->u8());
  q->wire_default = r->u8() != 0;
  uint64_t nd = r->vu();
  if (nd > (1u << 16)) return false;  // corrupt frame guard
  q->shape.clear();
  for (uint64_t i = 0; i < nd && r->ok(); ++i) q->shape.push_back(r->vi());
  uint64_t ns = r->vu();
  if (ns > (1u << 16)) return false;  // corrupt frame guard
  q->splits.clear();
  for (uint64_t i = 0; i < ns && r->ok(); ++i) q->splits.push_back(r->vi());
  return r->ok();
}

// Cache-hit slot ids travel bit-packed: varint bit count (highest set slot
// + 1, 0 when no hits) followed by ceil(nbits/8) bytes.  Slot ids are
// dense and bounded by HOROVOD_CACHE_CAPACITY, so a steady-state cycle's
// whole readiness report is a handful of bytes.
static void SerializeSlotBitvector(const std::vector<uint32_t>& slots,
                                   Writer* w) {
  uint32_t nbits = 0;
  for (auto s : slots) nbits = std::max(nbits, s + 1);
  w->vu(nbits);
  std::vector<uint8_t> bits((nbits + 7) / 8, 0);
  for (auto s : slots) bits[s / 8] |= static_cast<uint8_t>(1u << (s % 8));
  for (auto b : bits) w->u8(b);
}

static bool ParseSlotBitvector(Reader* r, std::vector<uint32_t>* slots) {
  slots->clear();
  uint64_t nbits = r->vu();
  if (!r->ok() || nbits > (1u << 20)) return false;  // corrupt frame guard
  for (uint64_t byte = 0; byte < (nbits + 7) / 8; ++byte) {
    uint8_t b = r->u8();
    for (uint64_t i = 0; i < 8 && byte * 8 + i < nbits; ++i) {
      if (b & (1u << i)) {
        slots->push_back(static_cast<uint32_t>(byte * 8 + i));
      }
    }
  }
  return r->ok();
}

// Explicit slot lists (cached/evicted ids) go ascending delta-varint:
// sorted once, each id is encoded as its distance from the previous one —
// dense id ranges (the common case: smallest-first reuse keeps them low)
// collapse to one byte per slot.  Order was never semantic: the receiver
// applies evictions idempotently and executes cached slots in ascending
// id order anyway (the sort here IS that order).
static void SerializeSlotList(std::vector<uint32_t> slots, Writer* w) {
  std::sort(slots.begin(), slots.end());
  w->vu(slots.size());
  uint32_t prev = 0;
  for (auto s : slots) {
    w->vu(s - prev);
    prev = s;
  }
}

static bool ParseSlotList(Reader* r, std::vector<uint32_t>* slots) {
  slots->clear();
  uint64_t n = r->vu();
  if (n > (1u << 20)) return false;  // corrupt frame guard
  uint32_t prev = 0;
  for (uint64_t i = 0; i < n && r->ok(); ++i) {
    prev += static_cast<uint32_t>(r->vu());
    slots->push_back(prev);
  }
  return r->ok();
}

// Telemetry piggyback: counter deltas are varint-coded (small deltas —
// the steady-state common case — are one byte each), gauges zigzag.
void SerializeTelemEntry(const TelemEntry& t, Writer* w) {
  w->vi(t.rank);
  w->vu(static_cast<uint64_t>(t.nranks));
  w->vu(static_cast<uint64_t>(t.host));
  w->vi(t.step_p50);
  w->vi(t.step_p99);
  w->vi(t.slow_rank);
  w->vi(t.slow_p99);
  w->vu(t.deltas.size());
  for (auto d : t.deltas) w->vi(d);
}

static bool ParseTelemEntry(Reader* r, TelemEntry* t) {
  t->rank = static_cast<int32_t>(r->vi());
  t->nranks = static_cast<int32_t>(r->vu());
  t->host = static_cast<int32_t>(r->vu());
  t->step_p50 = r->vi();
  t->step_p99 = r->vi();
  t->slow_rank = static_cast<int32_t>(r->vi());
  t->slow_p99 = r->vi();
  uint64_t n = r->vu();
  if (n > (1u << 10)) return false;  // corrupt frame guard
  t->deltas.clear();
  for (uint64_t i = 0; i < n && r->ok(); ++i) t->deltas.push_back(r->vi());
  return r->ok();
}

void SerializeRequestList(const RequestList& list, Writer* w) {
  w->vi(list.epoch);
  w->u8(list.shutdown ? 1 : 0);
  w->vu(list.requests.size());
  for (const auto& q : list.requests) SerializeRequest(q, w);
  SerializeSlotBitvector(list.cache_hits, w);
  SerializeSlotList(list.cache_evicts, w);
  // Sub-coordinator member-failure report behind a flag byte: the
  // healthy frame grows by exactly one byte.
  w->u8(list.fail_rank >= 0 ? 1 : 0);
  if (list.fail_rank >= 0) {
    w->vi(list.fail_rank);
    w->str(list.fail_message);
  }
  // Trailing TAGGED sections, each appended ONLY when present, so a
  // frame without any is byte-identical to the pre-section protocol
  // (the parser gates on remaining bytes, then dispatches on the tag).
  //
  // Tag 2: per-request scheduling priorities — only the NONZERO entries
  // travel, as (request index, priority) varint pairs parallel to the
  // `requests` vector, so an all-default frame (every frontend that
  // never stamps priorities) costs nothing.
  {
    uint64_t nonzero = 0;
    for (const auto& q : list.requests) {
      if (q.priority != 0) ++nonzero;
    }
    if (nonzero > 0) {
      w->u8(2);
      w->vu(nonzero);
      for (size_t i = 0; i < list.requests.size(); ++i) {
        if (list.requests[i].priority == 0) continue;
        w->vu(i);
        w->vu(static_cast<uint64_t>(list.requests[i].priority));
      }
    }
  }
  // Tag 1: fleet-telemetry piggyback (HOROVOD_TELEMETRY_CYCLES).
  if (!list.telem.empty()) {
    w->u8(1);
    w->vu(list.telem.size());
    for (const auto& t : list.telem) SerializeTelemEntry(t, w);
  }
}

bool ParseRequestList(Reader* r, RequestList* out) {
  out->epoch = r->vi();
  out->shutdown = r->u8() != 0;
  uint64_t n = r->vu();
  if (n > (1u << 20)) return false;
  out->requests.resize(n);
  for (uint64_t i = 0; i < n; ++i) {
    if (!ParseRequest(r, &out->requests[i])) return false;
  }
  if (!ParseSlotBitvector(r, &out->cache_hits)) return false;
  if (!ParseSlotList(r, &out->cache_evicts)) return false;
  if (r->u8() != 0) {
    out->fail_rank = static_cast<int32_t>(r->vi());
    out->fail_message = r->str();
  } else {
    out->fail_rank = -1;
    out->fail_message.clear();
  }
  out->telem.clear();
  // Trailing tagged sections (absence is the flag; see the serializer).
  while (r->ok() && r->remaining() > 0) {
    uint8_t tag = r->u8();
    if (tag == 1) {
      uint64_t n = r->vu();
      if (n > (1u << 16)) return false;
      out->telem.resize(n);
      for (uint64_t i = 0; i < n; ++i) {
        if (!ParseTelemEntry(r, &out->telem[i])) return false;
      }
    } else if (tag == 2) {
      uint64_t n = r->vu();
      if (n > (1u << 20)) return false;
      for (uint64_t i = 0; i < n && r->ok(); ++i) {
        uint64_t idx = r->vu();
        uint64_t prio = r->vu();
        if (idx >= out->requests.size() || prio > (1u << 30)) return false;
        out->requests[idx].priority = static_cast<int32_t>(prio);
      }
    } else {
      return false;  // unknown trailing section
    }
  }
  return r->ok();
}

static void SerializeResponse(const Response& s, Writer* w) {
  w->u8(static_cast<uint8_t>(s.type));
  w->vu(s.tensor_names.size());
  for (const auto& n : s.tensor_names) w->str(n);
  w->str(s.error_message);
  w->vu(s.tensor_sizes.size());
  for (auto v : s.tensor_sizes) w->vi(v);
  w->vi(s.root_rank);
  w->u8(static_cast<uint8_t>(s.red_op));
  w->u8(static_cast<uint8_t>(s.wire_dtype));
  w->vu(s.cache_slots.size());
  for (auto c : s.cache_slots) w->vi(c);
  // Backup-worker participant set behind a flag byte: the k=0 (and every
  // full-commit) frame grows by exactly one byte.
  w->u8(s.participants.empty() ? 0 : 1);
  if (!s.participants.empty()) {
    SerializeSlotBitvector(s.participants, w);
    w->vi(s.partial_elems);
    w->u8(s.partial_dtype);
  }
}

static bool ParseResponse(Reader* r, Response* s) {
  s->type = static_cast<ResponseType>(r->u8());
  uint64_t n = r->vu();
  if (n > (1u << 20)) return false;
  s->tensor_names.resize(n);
  for (uint64_t i = 0; i < n; ++i) s->tensor_names[i] = r->str();
  s->error_message = r->str();
  uint64_t m = r->vu();
  if (m > (1u << 20)) return false;
  s->tensor_sizes.clear();
  for (uint64_t i = 0; i < m && r->ok(); ++i) {
    s->tensor_sizes.push_back(r->vi());
  }
  s->root_rank = static_cast<int32_t>(r->vi());
  s->red_op = static_cast<ReduceOp>(r->u8());
  s->wire_dtype = static_cast<WireDtype>(r->u8());
  uint64_t c = r->vu();
  if (c > (1u << 20)) return false;
  s->cache_slots.clear();
  for (uint64_t i = 0; i < c && r->ok(); ++i) {
    s->cache_slots.push_back(static_cast<int32_t>(r->vi()));
  }
  // Normalize: every tensor name has a slot entry (-1 = uncached), so
  // consumers can index the two vectors in lockstep unconditionally.
  s->cache_slots.resize(s->tensor_names.size(), -1);
  if (r->u8() != 0) {
    if (!ParseSlotBitvector(r, &s->participants)) return false;
    s->partial_elems = r->vi();
    s->partial_dtype = r->u8();
  } else {
    s->participants.clear();
    s->partial_elems = 0;
    s->partial_dtype = 0;
  }
  return r->ok();
}

void SerializeResponseList(const ResponseList& list, Writer* w) {
  w->vi(list.epoch);
  w->u8(list.shutdown ? 1 : 0);
  w->u8(list.abort ? 1 : 0);
  w->vi(list.abort_rank);
  w->str(list.abort_message);
  w->vu(list.responses.size());
  for (const auto& s : list.responses) SerializeResponse(s, w);
  SerializeSlotList(list.cached_slots, w);
  SerializeSlotList(list.evict_slots, w);
  // TUNE payload behind a flag byte: the steady-state (and autotune-off)
  // frame grows by exactly one byte.
  w->u8(list.tune ? 1 : 0);
  if (list.tune) {
    w->u8(list.tune_commit ? 1 : 0);
    w->vi(list.tune_trial_id);
    w->vi(list.tune_chunk_bytes);
    w->vi(list.tune_fusion_threshold);
    w->vi(list.tune_cycle_time_ms);
    w->vi(list.tune_wave_width);
    w->vi(list.tune_algo_threshold);
    w->vi(list.tune_wire_dtype);
    w->vi(list.tune_priority_bands);
    w->vu(list.tune_fusion_ladder.size());
    for (auto v : list.tune_fusion_ladder) w->vi(v);
  }
  // Backup-worker partial commits on the cached path: slot → committed
  // participant bitmap.  Empty on every full-commit cycle (one byte).
  w->vu(list.partial_slots.size());
  for (const auto& ps : list.partial_slots) {
    w->vu(ps.slot);
    SerializeSlotBitvector(ps.participants, w);
  }
  // Trailing TAGGED section (absence is the flag, like the RequestList's
  // piggybacks): tag 3 = committed response priorities — only the
  // NONZERO entries travel, as (response index, priority) pairs.  A
  // rank that joined a negotiation via a layout PROBE stamped priority
  // 0 locally while its peers stamped the committed value; shipping the
  // committed priorities keeps the (priority, name) dispatch order —
  // and with it the wave/channel pairing — identical on every rank.
  // All-zero (the default) and legacy frames stay byte-identical.
  {
    uint64_t nonzero = 0;
    for (const auto& s : list.responses) {
      if (s.priority > 0) ++nonzero;
    }
    if (nonzero > 0) {
      w->u8(3);
      w->vu(nonzero);
      for (size_t i = 0; i < list.responses.size(); ++i) {
        if (list.responses[i].priority <= 0) continue;
        w->vu(i);
        w->vu(static_cast<uint64_t>(list.responses[i].priority));
      }
    }
  }
}

bool ParseResponseList(Reader* r, ResponseList* out) {
  out->epoch = r->vi();
  out->shutdown = r->u8() != 0;
  out->abort = r->u8() != 0;
  out->abort_rank = static_cast<int32_t>(r->vi());
  out->abort_message = r->str();
  uint64_t n = r->vu();
  if (n > (1u << 20)) return false;
  out->responses.resize(n);
  for (uint64_t i = 0; i < n; ++i) {
    if (!ParseResponse(r, &out->responses[i])) return false;
  }
  if (!ParseSlotList(r, &out->cached_slots)) return false;
  if (!ParseSlotList(r, &out->evict_slots)) return false;
  out->tune = r->u8() != 0;
  if (out->tune) {
    out->tune_commit = r->u8() != 0;
    out->tune_trial_id = r->vi();
    out->tune_chunk_bytes = r->vi();
    out->tune_fusion_threshold = r->vi();
    out->tune_cycle_time_ms = static_cast<int32_t>(r->vi());
    out->tune_wave_width = static_cast<int32_t>(r->vi());
    out->tune_algo_threshold = r->vi();
    out->tune_wire_dtype = static_cast<int32_t>(r->vi());
    out->tune_priority_bands = r->vi();
    uint64_t nl = r->vu();
    if (nl > 64) return false;  // corrupt frame guard
    out->tune_fusion_ladder.clear();
    for (uint64_t i = 0; i < nl && r->ok(); ++i) {
      out->tune_fusion_ladder.push_back(r->vi());
    }
  }
  uint64_t nps = r->vu();
  if (nps > (1u << 20)) return false;
  out->partial_slots.resize(nps);
  for (uint64_t i = 0; i < nps && r->ok(); ++i) {
    out->partial_slots[i].slot = static_cast<uint32_t>(r->vu());
    if (!ParseSlotBitvector(r, &out->partial_slots[i].participants)) {
      return false;
    }
  }
  // Trailing tagged sections (see the serializer).
  while (r->ok() && r->remaining() > 0) {
    uint8_t tag = r->u8();
    if (tag == 3) {
      uint64_t n = r->vu();
      if (n > (1u << 20)) return false;
      for (uint64_t i = 0; i < n && r->ok(); ++i) {
        uint64_t idx = r->vu();
        uint64_t prio = r->vu();
        if (idx >= out->responses.size() || prio > (1u << 30)) {
          return false;
        }
        out->responses[idx].priority = static_cast<int32_t>(prio);
      }
    } else {
      return false;  // unknown trailing section
    }
  }
  return r->ok();
}

// -- link self-healing handshake validation --
// The frames travel raw (fixed-width int64s, same build both ends); the
// magic check is what distinguishes a genuine RESUME/ACK from a stray
// connect's garbage or a truncated read filled with zeros.
bool ValidLinkResume(const LinkResume& r) {
  return r.magic == kLinkResumeMagic && r.origin >= 0 && r.ring >= 0 &&
         r.channel >= 0 && r.seq >= 0;
}

bool ValidLinkResumeAck(const LinkResumeAck& a) {
  return a.magic == kLinkAckMagic && (a.ok == 0 || a.ok == 1) &&
         a.step >= 0 && a.offset >= 0;
}

}  // namespace hvd
