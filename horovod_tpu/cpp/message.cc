#include "message.h"

#include <algorithm>

namespace hvd {

static void SerializeRequest(const Request& q, Writer* w) {
  w->i32(q.request_rank);
  w->u8(static_cast<uint8_t>(q.type));
  w->u8(static_cast<uint8_t>(q.dtype));
  w->str(q.tensor_name);
  w->i32(q.root_rank);
  w->u8(static_cast<uint8_t>(q.red_op));
  w->u8(q.probe ? 1 : 0);
  w->u8(static_cast<uint8_t>(q.wire_dtype));
  w->u32(static_cast<uint32_t>(q.shape.size()));
  for (auto d : q.shape) w->i64(d);
}

static bool ParseRequest(Reader* r, Request* q) {
  q->request_rank = r->i32();
  q->type = static_cast<RequestType>(r->u8());
  q->dtype = static_cast<DataType>(r->u8());
  q->tensor_name = r->str();
  q->root_rank = r->i32();
  q->red_op = static_cast<ReduceOp>(r->u8());
  q->probe = r->u8() != 0;
  q->wire_dtype = static_cast<WireDtype>(r->u8());
  uint32_t nd = r->u32();
  q->shape.clear();
  for (uint32_t i = 0; i < nd && r->ok(); ++i) q->shape.push_back(r->i64());
  return r->ok();
}

// Cache-hit slot ids travel bit-packed: u32 bit count (highest set slot
// + 1, 0 when no hits) followed by ceil(nbits/8) bytes.  Slot ids are
// dense and bounded by HOROVOD_CACHE_CAPACITY, so a steady-state cycle's
// whole readiness report is a handful of bytes.
static void SerializeSlotBitvector(const std::vector<uint32_t>& slots,
                                   Writer* w) {
  uint32_t nbits = 0;
  for (auto s : slots) nbits = std::max(nbits, s + 1);
  w->u32(nbits);
  std::vector<uint8_t> bits((nbits + 7) / 8, 0);
  for (auto s : slots) bits[s / 8] |= static_cast<uint8_t>(1u << (s % 8));
  for (auto b : bits) w->u8(b);
}

static bool ParseSlotBitvector(Reader* r, std::vector<uint32_t>* slots) {
  slots->clear();
  uint32_t nbits = r->u32();
  if (!r->ok() || nbits > (1u << 20)) return false;  // corrupt frame guard
  for (uint32_t byte = 0; byte < (nbits + 7) / 8; ++byte) {
    uint8_t b = r->u8();
    for (int i = 0; i < 8 && byte * 8 + i < nbits; ++i) {
      if (b & (1u << i)) slots->push_back(byte * 8 + i);
    }
  }
  return r->ok();
}

static void SerializeSlotList(const std::vector<uint32_t>& slots, Writer* w) {
  w->u32(static_cast<uint32_t>(slots.size()));
  for (auto s : slots) w->u32(s);
}

static bool ParseSlotList(Reader* r, std::vector<uint32_t>* slots) {
  slots->clear();
  uint32_t n = r->u32();
  for (uint32_t i = 0; i < n && r->ok(); ++i) slots->push_back(r->u32());
  return r->ok();
}

void SerializeRequestList(const RequestList& list, Writer* w) {
  w->i64(list.epoch);
  w->u8(list.shutdown ? 1 : 0);
  w->u32(static_cast<uint32_t>(list.requests.size()));
  for (const auto& q : list.requests) SerializeRequest(q, w);
  SerializeSlotBitvector(list.cache_hits, w);
  SerializeSlotList(list.cache_evicts, w);
}

bool ParseRequestList(Reader* r, RequestList* out) {
  out->epoch = r->i64();
  out->shutdown = r->u8() != 0;
  uint32_t n = r->u32();
  out->requests.resize(n);
  for (uint32_t i = 0; i < n; ++i) {
    if (!ParseRequest(r, &out->requests[i])) return false;
  }
  if (!ParseSlotBitvector(r, &out->cache_hits)) return false;
  if (!ParseSlotList(r, &out->cache_evicts)) return false;
  return r->ok();
}

static void SerializeResponse(const Response& s, Writer* w) {
  w->u8(static_cast<uint8_t>(s.type));
  w->u32(static_cast<uint32_t>(s.tensor_names.size()));
  for (const auto& n : s.tensor_names) w->str(n);
  w->str(s.error_message);
  w->u32(static_cast<uint32_t>(s.tensor_sizes.size()));
  for (auto v : s.tensor_sizes) w->i64(v);
  w->i32(s.root_rank);
  w->u8(static_cast<uint8_t>(s.red_op));
  w->u8(static_cast<uint8_t>(s.wire_dtype));
  w->u32(static_cast<uint32_t>(s.cache_slots.size()));
  for (auto c : s.cache_slots) w->i32(c);
}

static bool ParseResponse(Reader* r, Response* s) {
  s->type = static_cast<ResponseType>(r->u8());
  uint32_t n = r->u32();
  s->tensor_names.resize(n);
  for (uint32_t i = 0; i < n; ++i) s->tensor_names[i] = r->str();
  s->error_message = r->str();
  uint32_t m = r->u32();
  s->tensor_sizes.clear();
  for (uint32_t i = 0; i < m && r->ok(); ++i) s->tensor_sizes.push_back(r->i64());
  s->root_rank = r->i32();
  s->red_op = static_cast<ReduceOp>(r->u8());
  s->wire_dtype = static_cast<WireDtype>(r->u8());
  uint32_t c = r->u32();
  s->cache_slots.clear();
  for (uint32_t i = 0; i < c && r->ok(); ++i) s->cache_slots.push_back(r->i32());
  // Normalize: every tensor name has a slot entry (-1 = uncached), so
  // consumers can index the two vectors in lockstep unconditionally.
  s->cache_slots.resize(s->tensor_names.size(), -1);
  return r->ok();
}

void SerializeResponseList(const ResponseList& list, Writer* w) {
  w->i64(list.epoch);
  w->u8(list.shutdown ? 1 : 0);
  w->u8(list.abort ? 1 : 0);
  w->i32(list.abort_rank);
  w->str(list.abort_message);
  w->u32(static_cast<uint32_t>(list.responses.size()));
  for (const auto& s : list.responses) SerializeResponse(s, w);
  SerializeSlotList(list.cached_slots, w);
  SerializeSlotList(list.evict_slots, w);
  // TUNE payload behind a flag byte: the steady-state (and autotune-off)
  // frame grows by exactly one byte.
  w->u8(list.tune ? 1 : 0);
  if (list.tune) {
    w->u8(list.tune_commit ? 1 : 0);
    w->i64(list.tune_trial_id);
    w->i64(list.tune_chunk_bytes);
    w->i64(list.tune_fusion_threshold);
    w->i32(list.tune_cycle_time_ms);
    w->i32(list.tune_wave_width);
    w->i64(list.tune_algo_threshold);
    w->i32(list.tune_wire_dtype);
  }
}

bool ParseResponseList(Reader* r, ResponseList* out) {
  out->epoch = r->i64();
  out->shutdown = r->u8() != 0;
  out->abort = r->u8() != 0;
  out->abort_rank = r->i32();
  out->abort_message = r->str();
  uint32_t n = r->u32();
  out->responses.resize(n);
  for (uint32_t i = 0; i < n; ++i) {
    if (!ParseResponse(r, &out->responses[i])) return false;
  }
  if (!ParseSlotList(r, &out->cached_slots)) return false;
  if (!ParseSlotList(r, &out->evict_slots)) return false;
  out->tune = r->u8() != 0;
  if (out->tune) {
    out->tune_commit = r->u8() != 0;
    out->tune_trial_id = r->i64();
    out->tune_chunk_bytes = r->i64();
    out->tune_fusion_threshold = r->i64();
    out->tune_cycle_time_ms = r->i32();
    out->tune_wave_width = r->i32();
    out->tune_algo_threshold = r->i64();
    out->tune_wire_dtype = r->i32();
  }
  return r->ok();
}

}  // namespace hvd
