#include "shm.h"

#include <dirent.h>
#include <fcntl.h>
#include <linux/futex.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <sys/syscall.h>
#include <time.h>
#include <unistd.h>

#include <cerrno>
#include <chrono>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <thread>

namespace hvd {

static constexpr uint32_t kShmMagic = 0x4d535648u;  // "HVSM"
static constexpr uint32_t kShmVersion = 1;
static constexpr size_t kHdrBytes = 4096;  // one page keeps the data aligned

// Spin budget before sleeping: co-located peers answer in microseconds, so
// a short spin wins the latency case while the futex bounds the burn when
// the peer is genuinely busy.  HOROVOD_SHM_SPIN overrides (0 = no spin).
static int SpinIters() {
  static const int iters = [] {
    const char* v = std::getenv("HOROVOD_SHM_SPIN");
    if (v != nullptr && v[0] != '\0') {
      long n = std::strtol(v, nullptr, 10);
      return static_cast<int>(n < 0 ? 0 : n);
    }
    return 4000;
  }();
  return iters;
}

// futex(2) probed once: sandboxed kernels with partial coverage degrade to
// the yield/sleep fallback instead of failing transfers.
static bool FutexWorks() {
  static const bool ok = [] {
    uint32_t word = 1;
    // FUTEX_WAIT with a mismatched expected value must return EAGAIN
    // immediately on a working implementation.
    long rc = syscall(SYS_futex, &word, FUTEX_WAIT, 0u, nullptr, nullptr, 0);
    return rc == -1 && errno == EAGAIN;
  }();
  return ok;
}

static void FutexWaitSlice(std::atomic<uint32_t>* word, uint32_t expect,
                           int ms) {
  timespec ts{ms / 1000, static_cast<long>(ms % 1000) * 1000000};
  syscall(SYS_futex, reinterpret_cast<uint32_t*>(word), FUTEX_WAIT, expect,
          &ts, nullptr, 0);
}

static void FutexWakeAll(std::atomic<uint32_t>* word) {
  syscall(SYS_futex, reinterpret_cast<uint32_t*>(word), FUTEX_WAKE,
          0x7fffffff, nullptr, nullptr, 0);
}

ShmRing& ShmRing::operator=(ShmRing&& o) noexcept {
  if (this != &o) {
    Unmap();
    hdr_ = o.hdr_;
    data_ = o.data_;
    map_len_ = o.map_len_;
    name_ = std::move(o.name_);
    creator_ = o.creator_;
    unlinked_ = o.unlinked_;
    o.hdr_ = nullptr;
    o.data_ = nullptr;
    o.map_len_ = 0;
    o.unlinked_ = true;
  }
  return *this;
}

bool ShmRing::Create(const std::string& name, uint64_t capacity,
                     int64_t epoch, std::string* err) {
  Unmap();
  // Stale same-name file (a crash mid-wiring in a dead incarnation that
  // happened to reuse the epoch counter): the name is ours to claim.
  ::shm_unlink(name.c_str());
  int fd = ::shm_open(name.c_str(), O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) {
    *err = "shm_open(" + name + "): " + strerror(errno);
    return false;
  }
  size_t len = kHdrBytes + capacity;
  if (::ftruncate(fd, static_cast<off_t>(len)) != 0) {
    *err = "ftruncate(" + name + "): " + strerror(errno) +
           " — is /dev/shm full? see docs/troubleshooting.md";
    ::close(fd);
    ::shm_unlink(name.c_str());
    return false;
  }
  void* p = ::mmap(nullptr, len, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  ::close(fd);
  if (p == MAP_FAILED) {
    *err = "mmap(" + name + "): " + strerror(errno);
    ::shm_unlink(name.c_str());
    return false;
  }
  hdr_ = new (p) ShmRingHdr();
  hdr_->magic = 0;  // published last, after the fields below are in place
  hdr_->version = kShmVersion;
  hdr_->epoch = epoch;
  hdr_->capacity = capacity;
  hdr_->head.store(0);
  hdr_->tail.store(0);
  hdr_->seq.store(0);
  hdr_->waiters.store(0);
  hdr_->closed.store(0);
  hdr_->attached.store(0);
  std::atomic_thread_fence(std::memory_order_release);
  hdr_->magic = kShmMagic;
  data_ = static_cast<uint8_t*>(p) + kHdrBytes;
  map_len_ = len;
  name_ = name;
  creator_ = true;
  unlinked_ = false;
  return true;
}

bool ShmRing::Attach(const std::string& name, int64_t epoch, int timeout_ms,
                     std::string* err) {
  Unmap();
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(timeout_ms);
  while (true) {
    int fd = ::shm_open(name.c_str(), O_RDWR, 0600);
    if (fd >= 0) {
      struct stat st {};
      if (::fstat(fd, &st) == 0 &&
          st.st_size > static_cast<off_t>(kHdrBytes)) {
        size_t len = static_cast<size_t>(st.st_size);
        void* p =
            ::mmap(nullptr, len, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
        ::close(fd);
        if (p == MAP_FAILED) {
          *err = "mmap(" + name + "): " + strerror(errno);
          return false;
        }
        ShmRingHdr* hdr = static_cast<ShmRingHdr*>(p);
        if (hdr->magic == kShmMagic && hdr->version == kShmVersion &&
            hdr->epoch == epoch &&
            len == kHdrBytes + hdr->capacity) {
          hdr_ = hdr;
          data_ = static_cast<uint8_t*>(p) + kHdrBytes;
          map_len_ = len;
          name_ = name;
          creator_ = false;
          unlinked_ = true;  // the creator owns the name
          hdr_->attached.store(1, std::memory_order_release);
          FutexWakeAll(&hdr_->seq);
          return true;
        }
        // Stale/mismatched segment (an older epoch's leftover the creator
        // is about to replace): unmap and keep retrying until the real one
        // appears.
        ::munmap(p, len);
      } else {
        ::close(fd);
      }
    }
    if (std::chrono::steady_clock::now() > deadline) {
      *err = "shm attach timed out waiting for " + name +
             " — the peer likely died during wiring";
      return false;
    }
    std::this_thread::sleep_for(std::chrono::milliseconds(2));
  }
}

bool ShmRing::UnlinkAfterAttach(int timeout_ms) {
  if (hdr_ == nullptr || !creator_ || unlinked_) return unlinked_;
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(timeout_ms);
  while (!hdr_->attached.load(std::memory_order_acquire)) {
    if (std::chrono::steady_clock::now() > deadline) return false;
    std::this_thread::sleep_for(std::chrono::milliseconds(1));
  }
  ::shm_unlink(name_.c_str());
  unlinked_ = true;
  return true;
}

void ShmRing::Close() {
  if (hdr_ == nullptr) return;
  hdr_->closed.store(1, std::memory_order_release);
  Bump();
  FutexWakeAll(&hdr_->seq);  // wake unconditionally: teardown is rare
}

void ShmRing::Unmap() {
  if (hdr_ == nullptr) return;
  if (creator_ && !unlinked_) {
    // Wiring never completed (init failure): drop the name so nothing
    // leaks; the coordinator's sweep is the backstop, not the norm.
    ::shm_unlink(name_.c_str());
    unlinked_ = true;
  }
  ::munmap(hdr_, map_len_);
  hdr_ = nullptr;
  data_ = nullptr;
  map_len_ = 0;
}

void ShmRing::Bump() {
  hdr_->seq.fetch_add(1, std::memory_order_acq_rel);
  if (hdr_->waiters.load(std::memory_order_acquire) != 0) {
    FutexWakeAll(&hdr_->seq);
  }
}

size_t ShmRing::TryWrite(const void* p, size_t n) {
  uint64_t head = hdr_->head.load(std::memory_order_relaxed);
  uint64_t tail = hdr_->tail.load(std::memory_order_acquire);
  uint64_t space = hdr_->capacity - (head - tail);
  if (space == 0 || n == 0) return 0;
  size_t k = static_cast<size_t>(space < n ? space : n);
  uint64_t off = head % hdr_->capacity;
  size_t first = static_cast<size_t>(
      hdr_->capacity - off < k ? hdr_->capacity - off : k);
  memcpy(data_ + off, p, first);
  if (k > first) {
    memcpy(data_, static_cast<const uint8_t*>(p) + first, k - first);
  }
  hdr_->head.store(head + k, std::memory_order_release);
  Bump();
  return k;
}

size_t ShmRing::TryRead(void* p, size_t n) {
  uint64_t head = hdr_->head.load(std::memory_order_acquire);
  uint64_t tail = hdr_->tail.load(std::memory_order_relaxed);
  uint64_t avail = head - tail;
  if (avail == 0 || n == 0) return 0;
  size_t k = static_cast<size_t>(avail < n ? avail : n);
  uint64_t off = tail % hdr_->capacity;
  size_t first = static_cast<size_t>(
      hdr_->capacity - off < k ? hdr_->capacity - off : k);
  memcpy(p, data_ + off, first);
  if (k > first) {
    memcpy(static_cast<uint8_t*>(p) + first, data_, k - first);
  }
  hdr_->tail.store(tail + k, std::memory_order_release);
  Bump();
  return k;
}

void ShmRing::WaitSeqSlice(uint32_t seen, int timeout_ms) {
  if (FutexWorks()) {
    hdr_->waiters.fetch_add(1, std::memory_order_acq_rel);
    if (hdr_->seq.load(std::memory_order_acquire) == seen &&
        !Closed()) {
      FutexWaitSlice(&hdr_->seq, seen, timeout_ms);
    }
    hdr_->waiters.fetch_sub(1, std::memory_order_acq_rel);
  } else {
    // Spin-then-yield fallback for kernels without a working futex: sleep
    // a short slice — correctness never depends on the wakeup, only
    // latency does.
    std::this_thread::sleep_for(std::chrono::microseconds(200));
  }
}

template <typename Avail>
static bool WaitCond(ShmRing* ring, ShmRingHdr* hdr, Avail avail,
                     int timeout_ms) {
  // One progress bound: `timeout_ms` of NO seq movement (not total wait —
  // a peer legitimately mid-collective keeps bumping seq).
  int spin = SpinIters();
  auto last_change = std::chrono::steady_clock::now();
  uint32_t seen = hdr->seq.load(std::memory_order_acquire);
  for (int i = 0;; ++i) {
    if (avail() > 0) return true;
    if (ring->Closed()) return false;
    uint32_t now_seq = hdr->seq.load(std::memory_order_acquire);
    if (now_seq != seen) {
      seen = now_seq;
      last_change = std::chrono::steady_clock::now();
      continue;
    }
    if (timeout_ms > 0 &&
        std::chrono::steady_clock::now() - last_change >
            std::chrono::milliseconds(timeout_ms)) {
      return false;
    }
    if (i < spin) {
#if defined(__x86_64__)
      __builtin_ia32_pause();
#endif
      continue;
    }
    if (i < spin + 64) {
      std::this_thread::yield();
      continue;
    }
    ring->WaitSeqSlice(seen, 10);
  }
}

bool ShmRing::WaitReadable(int timeout_ms) {
  if (hdr_ == nullptr) return false;
  return WaitCond(this, hdr_, [&] { return ReadAvail(); }, timeout_ms);
}

bool ShmRing::WaitWritable(int timeout_ms) {
  if (hdr_ == nullptr) return false;
  return WaitCond(this, hdr_, [&] { return WriteAvail(); }, timeout_ms);
}

bool ShmRing::WriteAll(const void* p, size_t n, int timeout_ms,
                       std::string* err) {
  const uint8_t* b = static_cast<const uint8_t*>(p);
  while (n > 0) {
    size_t k = TryWrite(b, n);
    if (k == 0) {
      if (Closed()) {
        *err = "send to peer: shm ring closed (peer exited?)";
        return false;
      }
      if (!WaitWritable(timeout_ms)) {
        *err = Closed() ? "send to peer: shm ring closed (peer exited?)"
                        : "send to peer: shm no progress for " +
                              std::to_string(timeout_ms / 1000) +
                              "s (peer hung?)";
        return false;
      }
      continue;
    }
    b += k;
    n -= k;
  }
  return true;
}

bool ShmRing::ReadAll(void* p, size_t n, int timeout_ms, std::string* err) {
  uint8_t* b = static_cast<uint8_t*>(p);
  while (n > 0) {
    size_t k = TryRead(b, n);
    if (k == 0) {
      // Drain-before-close: bytes already in the ring stay readable after
      // a Close, so only an EMPTY closed ring is EOF.
      if (Closed() && ReadAvail() == 0) {
        *err = "recv from peer: shm ring closed (peer exited?)";
        return false;
      }
      if (!WaitReadable(timeout_ms)) {
        *err = Closed() ? "recv from peer: shm ring closed (peer exited?)"
                        : "recv from peer: shm no progress for " +
                              std::to_string(timeout_ms / 1000) +
                              "s (peer hung?)";
        return false;
      }
      continue;
    }
    b += k;
    n -= k;
  }
  return true;
}

bool ShmSendRecvChunked(ShmRing& tx, const void* send_buf, size_t sn,
                        ShmRing& rx, void* recv_buf, size_t rn, size_t chunk,
                        const std::function<void(size_t, size_t)>& on_chunk,
                        int timeout_ms, std::string* err, int64_t* wire_ns) {
  const uint8_t* sp = static_cast<const uint8_t*>(send_buf);
  uint8_t* rp = static_cast<uint8_t*>(recv_buf);
  const size_t rtotal = rn;
  size_t delivered = 0;
  if (chunk == 0) chunk = rtotal;
  const int spin = SpinIters();
  auto t0 = std::chrono::steady_clock::now();
  auto last_progress = t0;
  int64_t cb_ns = 0;
  int idle = 0;
  while (sn > 0 || rn > 0) {
    bool progress = false;
    if (sn > 0) {
      size_t k = tx.TryWrite(sp, sn);
      if (k > 0) {
        sp += k;
        sn -= k;
        progress = true;
      } else if (tx.Closed()) {
        *err = "send to peer: shm ring closed (peer exited?)";
        return false;
      }
    }
    if (rn > 0) {
      size_t k = rx.TryRead(rp, rn);
      if (k > 0) {
        rp += k;
        rn -= k;
        progress = true;
        if (on_chunk) {
          size_t done = rtotal - rn;
          while (delivered < done &&
                 (done - delivered >= chunk || rn == 0)) {
            size_t len = chunk < done - delivered ? chunk : done - delivered;
            auto c0 = std::chrono::steady_clock::now();
            on_chunk(delivered, len);
            cb_ns += std::chrono::duration_cast<std::chrono::nanoseconds>(
                         std::chrono::steady_clock::now() - c0)
                         .count();
            delivered += len;
          }
        }
      } else if (rx.Closed() && rx.ReadAvail() == 0) {
        *err = "recv from peer: shm ring closed (peer exited?)";
        return false;
      }
    }
    if (progress) {
      idle = 0;
      last_progress = std::chrono::steady_clock::now();
      continue;
    }
    if (timeout_ms > 0 &&
        std::chrono::steady_clock::now() - last_progress >
            std::chrono::milliseconds(timeout_ms)) {
      *err = "link: shm no progress for " +
             std::to_string(timeout_ms / 1000) + "s (peer hung?)";
      return false;
    }
    ++idle;
    if (idle < spin) {
#if defined(__x86_64__)
      __builtin_ia32_pause();
#endif
    } else if (idle < spin + 64) {
      std::this_thread::yield();
    } else {
      // Bounded nap: with both directions pending we cannot futex-wait on
      // two words at once; the slice is short enough that throughput never
      // notices and long enough that an idle wait stops burning the core.
      std::this_thread::sleep_for(std::chrono::microseconds(100));
    }
  }
  if (wire_ns != nullptr) {
    *wire_ns += std::chrono::duration_cast<std::chrono::nanoseconds>(
                    std::chrono::steady_clock::now() - t0)
                    .count() -
                cb_ns;
  }
  return true;
}

int ShmSweepStale(const std::string& prefix,
                  const std::string& keep_substr) {
  DIR* d = ::opendir("/dev/shm");
  if (d == nullptr) return 0;
  int n = 0;
  while (dirent* e = ::readdir(d)) {
    if (strncmp(e->d_name, prefix.c_str(), prefix.size()) == 0) {
      if (!keep_substr.empty() &&
          strstr(e->d_name, keep_substr.c_str()) != nullptr) {
        continue;  // a live peer's current-epoch segment mid-wiring
      }
      std::string name = "/";
      name += e->d_name;
      if (::shm_unlink(name.c_str()) == 0) ++n;
    }
  }
  ::closedir(d);
  if (n > 0) {
    std::fprintf(stderr,
                 "horovod_tpu: swept %d stale shm segment(s) with prefix "
                 "%s\n",
                 n, prefix.c_str());
  }
  return n;
}

bool ShmAvailable() {
  static const bool ok = [] {
    char name[64];
    std::snprintf(name, sizeof(name), "/hvd_probe_%d", ::getpid());
    ::shm_unlink(name);
    int fd = ::shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
    if (fd < 0) return false;
    bool good = ::ftruncate(fd, 4096) == 0;
    void* p = good ? ::mmap(nullptr, 4096, PROT_READ | PROT_WRITE,
                            MAP_SHARED, fd, 0)
                   : MAP_FAILED;
    if (p != MAP_FAILED) ::munmap(p, 4096);
    ::close(fd);
    ::shm_unlink(name);
    return good && p != MAP_FAILED;
  }();
  return ok;
}

}  // namespace hvd
