// Control-plane flight recorder: a fixed-size in-memory ring of the last
// HOROVOD_FLIGHT_RECORDER_EVENTS control-plane events per rank (cycle
// summaries, response commits, cache evictions, partial commits, TUNE
// applies, epoch moves, stall warnings, abort verdicts), dumped
// atomically to HOROVOD_FLIGHT_RECORDER_DIR as
// ``flightrec.rank<r>.json`` on abort, stall-warning escalation, and
// fatal signals — the post-mortem CLI
// (``python -m horovod_tpu.monitor.postmortem``) cross-correlates the
// per-rank dumps and names the divergence point.
//
// Constraints that shape the design:
//   * recording happens on the background (control) thread every payload
//     cycle — it must be a couple of snprintf's into preallocated
//     fixed-size slots, never an allocation;
//   * the fatal-signal dump path cannot malloc or take a blocking lock —
//     events are POD, the writer is open/write/rename, and the ring lock
//     is a try-spin that the signal path simply skips (a torn in-flight
//     event is acceptable in a crash dump; a hang is not).
#pragma once

#include <atomic>
#include <cstdint>
#include <string>

namespace hvd {

class FlightRecorder {
 public:
  // kinds are short stable strings the post-mortem CLI switches on.
  static constexpr int kKindLen = 16;
  static constexpr int kTextLen = 168;
  struct Event {
    int64_t seq = 0;        // global record sequence (gap-free per rank)
    int64_t mono_ns = 0;    // steady_clock since an arbitrary epoch
    int64_t cycle = 0;      // control-plane cycle counter at record time
    char kind[kKindLen] = {0};
    char text[kTextLen] = {0};
  };

  // capacity <= 0 disables recording entirely; dir may be empty
  // (recording without a dump sink still feeds horovod_flight_events).
  void Configure(int capacity, const std::string& dir, int rank,
                 int64_t epoch, int64_t clock_offset_ns);
  bool enabled() const { return capacity_ > 0; }
  int64_t events_recorded() const { return seq_.load(); }
  int64_t dumps_written() const { return dumps_.load(); }

  // printf-style, truncating at kTextLen.  Cheap no-op when disabled.
  void Record(const char* kind, int64_t cycle, const char* fmt, ...)
      __attribute__((format(printf, 4, 5)));

  // Write the ring to <dir>/flightrec.rank<r>.json (tmp + rename).
  // `reason` lands in the dump header.  signal_safe=true skips the lock
  // and uses only async-signal-safe syscalls after the formatting.
  // Returns 0 on success, -1 when disabled/no dir/IO failure.  Repeated
  // dumps overwrite (the latest state wins).
  int Dump(const char* reason, bool signal_safe = false);

  ~FlightRecorder();

 private:
  Event* ring_ = nullptr;
  int capacity_ = 0;
  int rank_ = 0;
  int64_t epoch_ = 0;
  int64_t clock_offset_ns_ = 0;
  char dir_[256] = {0};
  std::atomic<int64_t> seq_{0};
  std::atomic<int64_t> dumps_{0};
  // Spin guard for slot formatting; Dump(signal_safe) skips it.
  std::atomic_flag lock_ = ATOMIC_FLAG_INIT;
};

// Process-wide recorder (the engine singleton's lifetime matches the
// process; the fatal-signal handler needs a global to reach).
FlightRecorder& GlobalFlightRecorder();

// Install SIGSEGV/SIGBUS/SIGFPE/SIGABRT/SIGTERM handlers that dump the
// recorder before re-raising the default action.  Idempotent.
void InstallFlightSignalHandlers();

}  // namespace hvd
