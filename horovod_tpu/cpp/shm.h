// Shared-memory transport for co-located ranks.
//
// The reference delegates intra-host transport to NCCL, which picks shm/P2P
// under the hood; this runtime's loopback-TCP ring is CPU-ceilinged on small
// hosts (~1.4 GB/s aggregate on the 2-core CI box) and every byte between
// same-host ranks paid syscall + copy tax twice.  ShmRing is the second
// channel kind of the data plane: a single-producer/single-consumer byte
// ring in a POSIX shm segment (/dev/shm), mapped by exactly two processes,
// with monotonic head/tail cursors and a futex wakeup — plus a
// spin-then-yield fallback, because sandboxed kernels have spotty syscall
// coverage (the gVisor accept(2)/SO_RCVTIMEO precedent; futex is probed at
// runtime, never assumed).
//
// Lifecycle is leak-proof by construction: the creator unlinks the segment
// the moment the attacher confirms its mapping (unlink-after-map — the
// mapping survives, the name does not), so a killed job leaves no /dev/shm
// entries behind for wired edges, and the coordinator sweeps the job's name
// prefix at every rendezvous so a crash DURING wiring is cleaned up by the
// next incarnation (elastic re-init, supervisor relaunch).
#pragma once

#include <atomic>
#include <cstdint>
#include <functional>
#include <string>

namespace hvd {

// Segment header, one page; the byte ring follows it.  head/tail are
// MONOTONIC byte counters (no wrap ambiguity): read avail = head - tail,
// write avail = capacity - (head - tail).  `seq` is the futex word — bumped
// by every publish/consume so a waiter can sleep on "no state change";
// `waiters` gates the wake syscall (the common case never pays it).
struct ShmRingHdr {
  uint32_t magic;
  uint32_t version;
  int64_t epoch;
  uint64_t capacity;
  alignas(64) std::atomic<uint64_t> head;      // producer-written
  alignas(64) std::atomic<uint64_t> tail;      // consumer-written
  alignas(64) std::atomic<uint32_t> seq;       // futex word (state changes)
  std::atomic<uint32_t> waiters;
  std::atomic<uint32_t> closed;                // either side's EOF/abort
  std::atomic<uint32_t> attached;              // attacher confirms mapping
};

// One direction of a co-located edge.  The CREATOR is always the PRODUCER
// (edge source); the attacher is the consumer — fixed roles keep the SPSC
// contract self-evident at every call site.
class ShmRing {
 public:
  ShmRing() = default;
  ~ShmRing() { Unmap(); }
  ShmRing(const ShmRing&) = delete;
  ShmRing& operator=(const ShmRing&) = delete;
  ShmRing(ShmRing&& o) noexcept { *this = std::move(o); }
  ShmRing& operator=(ShmRing&& o) noexcept;

  // Producer side: create the segment (unlinking any stale same-name file
  // first — names are epoch-stamped, so a live segment can never collide).
  bool Create(const std::string& name, uint64_t capacity, int64_t epoch,
              std::string* err);
  // Consumer side: attach, retrying until the creator's segment appears
  // (bounded by timeout_ms); validates magic + epoch, confirms the mapping
  // via hdr->attached so the creator can unlink.
  bool Attach(const std::string& name, int64_t epoch, int timeout_ms,
              std::string* err);
  // Producer side, post-wiring: wait for the attach confirmation, then
  // unlink the name (the mapping stays alive; the filesystem entry — the
  // only thing a kill could leak — is gone).  False on timeout.
  bool UnlinkAfterAttach(int timeout_ms);

  bool valid() const { return hdr_ != nullptr; }
  // Peer (or self) closed the ring — the shm analogue of TCP EOF.
  bool Closed() const {
    return hdr_ == nullptr || hdr_->closed.load(std::memory_order_acquire);
  }
  // Mark closed + wake any sleeper, so a blocked peer fails fast instead
  // of waiting out its timeout (Engine teardown calls this on every ring).
  void Close();
  void Unmap();

  uint64_t ReadAvail() const {
    return hdr_->head.load(std::memory_order_acquire) -
           hdr_->tail.load(std::memory_order_relaxed);
  }
  uint64_t WriteAvail() const {
    return hdr_->capacity - (hdr_->head.load(std::memory_order_relaxed) -
                             hdr_->tail.load(std::memory_order_acquire));
  }

  // Nonblocking SPSC transfers; return bytes moved (0 = full/empty).
  size_t TryWrite(const void* p, size_t n);
  size_t TryRead(void* p, size_t n);

  // Block (spin, then futex/yield) until data/space is available, the ring
  // closes, or timeout_ms of NO state change elapses (<= 0: wait forever).
  // True = condition may hold now; false = timeout or closed (check
  // Closed() to tell them apart).
  bool WaitReadable(int timeout_ms);
  bool WaitWritable(int timeout_ms);

  // Blocking whole-buffer helpers over the primitives above; on failure
  // *err says whether the peer closed or stalled past timeout_ms.
  bool WriteAll(const void* p, size_t n, int timeout_ms, std::string* err);
  bool ReadAll(void* p, size_t n, int timeout_ms, std::string* err);

  // One bounded sleep slice on "seq still == seen" (futex when the kernel
  // has one, a short nap otherwise).  Used by the wait loops; public so
  // free-function progress loops can park on a ring without friending.
  void WaitSeqSlice(uint32_t seen, int timeout_ms);

 private:
  void Bump();   // publish a state change: seq++ (+ futex wake if waited-on)

  ShmRingHdr* hdr_ = nullptr;
  uint8_t* data_ = nullptr;
  size_t map_len_ = 0;
  std::string name_;
  bool creator_ = false;
  bool unlinked_ = false;
};

// A duplex co-located edge: tx carries this rank's bytes toward the peer,
// rx the reverse direction (each an independently created/attached ring).
struct ShmEdge {
  ShmRing tx, rx;
  bool valid() const { return tx.valid() && rx.valid(); }
};

// Full-duplex chunked transfer over an edge — the shm analogue of
// SendRecvChunked (socket.h): stream sn bytes out and rn bytes in
// simultaneously, firing on_chunk(offset, len) as every completed `chunk`
// of the receive lands (0 = one callback at the end).  Spin-then-yield
// progress loop; timeout_ms bounds time with NO forward progress.  When
// non-null, wire_ns accumulates loop time minus callback time.
bool ShmSendRecvChunked(ShmRing& tx, const void* send_buf, size_t sn,
                        ShmRing& rx, void* recv_buf, size_t rn, size_t chunk,
                        const std::function<void(size_t, size_t)>& on_chunk,
                        int timeout_ms, std::string* err,
                        int64_t* wire_ns = nullptr);

// Unlink every /dev/shm entry whose name starts with `prefix`, except
// names containing `keep_substr` (when non-empty).  The coordinator calls
// this between the membership commit and the ASSIGN broadcast — no
// current-epoch segment exists yet (workers create edges only after
// ASSIGN), so everything matching is a dead incarnation's leftover from a
// crash mid-wiring.  Group leaders on other hosts sweep during wiring and
// pass the current epoch tag as `keep_substr` so live peers' fresh
// segments survive.  Returns the number unlinked.
int ShmSweepStale(const std::string& prefix,
                  const std::string& keep_substr = std::string());

// One-shot runtime probe: can this host create + map + unlink a segment?
// The coordinator folds the answer into the committed shm_enabled flag so
// every rank agrees on the transport (a per-rank fallback would desync the
// wire pattern).
bool ShmAvailable();

}  // namespace hvd
