#include "socket.h"

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cerrno>
#include <chrono>
#include <cstring>
#include <thread>

namespace hvd {

Socket::~Socket() { Close(); }

Socket& Socket::operator=(Socket&& o) noexcept {
  if (this != &o) {
    Close();
    fd_ = o.fd_;
    o.fd_ = -1;
  }
  return *this;
}

void Socket::Close() {
  if (fd_ >= 0) {
    ::close(fd_);
    fd_ = -1;
  }
}

bool Socket::SendAll(const void* data, size_t n) {
  const char* p = static_cast<const char*>(data);
  while (n > 0) {
    ssize_t sent = ::send(fd_, p, n, MSG_NOSIGNAL);
    if (sent <= 0) {
      if (sent < 0 && (errno == EINTR)) continue;
      return false;
    }
    p += sent;
    n -= static_cast<size_t>(sent);
  }
  return true;
}

bool Socket::RecvAll(void* data, size_t n) {
  char* p = static_cast<char*>(data);
  while (n > 0) {
    ssize_t got = ::recv(fd_, p, n, 0);
    if (got <= 0) {
      if (got < 0 && errno == EINTR) continue;
      return false;
    }
    p += got;
    n -= static_cast<size_t>(got);
  }
  return true;
}

bool Socket::SendFrame(const std::vector<uint8_t>& payload) {
  uint64_t len = payload.size();
  if (!SendAll(&len, sizeof(len))) return false;
  if (len == 0) return true;
  return SendAll(payload.data(), payload.size());
}

bool Socket::RecvFrame(std::vector<uint8_t>* payload) {
  uint64_t len = 0;
  if (!RecvAll(&len, sizeof(len))) return false;
  if (len > (1ull << 34)) return false;  // 16 GB sanity cap
  payload->resize(len);
  if (len == 0) return true;
  return RecvAll(payload->data(), len);
}

static void SetNoDelay(int fd) {
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
}

Socket Listen(const std::string& host, int port, int backlog,
              int* bound_port, std::string* error) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) {
    *error = std::string("socket: ") + strerror(errno);
    return Socket();
  }
  int one = 1;
  ::setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (host.empty() || host == "0.0.0.0") {
    addr.sin_addr.s_addr = INADDR_ANY;
  } else if (::inet_pton(AF_INET, host.c_str(), &addr.sin_addr) != 1) {
    hostent* he = ::gethostbyname(host.c_str());
    if (he == nullptr || he->h_addr_list[0] == nullptr) {
      *error = "cannot resolve host " + host;
      ::close(fd);
      return Socket();
    }
    memcpy(&addr.sin_addr, he->h_addr_list[0], sizeof(addr.sin_addr));
  }
  if (::bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
    *error = std::string("bind: ") + strerror(errno);
    ::close(fd);
    return Socket();
  }
  if (::listen(fd, backlog) != 0) {
    *error = std::string("listen: ") + strerror(errno);
    ::close(fd);
    return Socket();
  }
  if (bound_port != nullptr) {
    sockaddr_in got{};
    socklen_t len = sizeof(got);
    ::getsockname(fd, reinterpret_cast<sockaddr*>(&got), &len);
    *bound_port = ntohs(got.sin_port);
  }
  return Socket(fd);
}

Socket Accept(Socket& listener, std::string* error) {
  int fd = ::accept(listener.fd(), nullptr, nullptr);
  if (fd < 0) {
    *error = std::string("accept: ") + strerror(errno);
    return Socket();
  }
  SetNoDelay(fd);
  return Socket(fd);
}

Socket ConnectRetry(const std::string& host, int port, int deadline_ms,
                    std::string* error) {
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(deadline_ms);
  std::string last_err;
  while (std::chrono::steady_clock::now() < deadline) {
    int fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) {
      last_err = std::string("socket: ") + strerror(errno);
      break;
    }
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (::inet_pton(AF_INET, host.c_str(), &addr.sin_addr) != 1) {
      hostent* he = ::gethostbyname(host.c_str());
      if (he == nullptr || he->h_addr_list[0] == nullptr) {
        *error = "cannot resolve host " + host;
        ::close(fd);
        return Socket();
      }
      memcpy(&addr.sin_addr, he->h_addr_list[0], sizeof(addr.sin_addr));
    }
    if (::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) == 0) {
      SetNoDelay(fd);
      return Socket(fd);
    }
    last_err = std::string("connect: ") + strerror(errno);
    ::close(fd);
    std::this_thread::sleep_for(std::chrono::milliseconds(20));
  }
  *error = "timed out connecting to " + host + ":" + std::to_string(port) +
           " (" + last_err + ")";
  return Socket();
}

}  // namespace hvd
