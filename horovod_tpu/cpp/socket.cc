#include "socket.h"

#include <arpa/inet.h>
#include <fcntl.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <sys/time.h>
#include <unistd.h>

#include <algorithm>
#include <cerrno>
#include <chrono>
#include <cstring>
#include <thread>

namespace hvd {

Socket::~Socket() { Close(); }

Socket& Socket::operator=(Socket&& o) noexcept {
  if (this != &o) {
    Close();
    fd_ = o.fd_;
    o.fd_ = -1;
  }
  return *this;
}

void Socket::Close() {
  if (fd_ >= 0) {
    ::close(fd_);
    fd_ = -1;
  }
}

void Socket::SetTimeouts(int timeout_sec) {
  if (fd_ < 0 || timeout_sec <= 0) return;
  timeval tv{};
  tv.tv_sec = timeout_sec;
  ::setsockopt(fd_, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
  ::setsockopt(fd_, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
}

void Socket::SetBufSizes(int bytes) {
  if (fd_ < 0 || bytes <= 0) return;
  ::setsockopt(fd_, SOL_SOCKET, SO_SNDBUF, &bytes, sizeof(bytes));
  ::setsockopt(fd_, SOL_SOCKET, SO_RCVBUF, &bytes, sizeof(bytes));
}

bool Socket::SendAll(const void* data, size_t n) {
  const char* p = static_cast<const char*>(data);
  while (n > 0) {
    ssize_t sent = ::send(fd_, p, n, MSG_NOSIGNAL);
    if (sent <= 0) {
      if (sent < 0 && (errno == EINTR)) continue;
      return false;
    }
    p += sent;
    n -= static_cast<size_t>(sent);
  }
  return true;
}

bool Socket::RecvAll(void* data, size_t n) {
  char* p = static_cast<char*>(data);
  while (n > 0) {
    ssize_t got = ::recv(fd_, p, n, 0);
    if (got <= 0) {
      if (got < 0 && errno == EINTR) continue;
      return false;
    }
    p += got;
    n -= static_cast<size_t>(got);
  }
  return true;
}

bool Socket::RecvAllPatient(void* data, size_t n, int max_idle_rounds,
                            const char* wait_label) {
  char* p = static_cast<char*>(data);
  int idle = 0;
  while (n > 0) {
    ssize_t got = ::recv(fd_, p, n, 0);
    if (got <= 0) {
      if (got < 0 && errno == EINTR) continue;
      if (got < 0 && (errno == EAGAIN || errno == EWOULDBLOCK) &&
          ++idle <= max_idle_rounds) {
        // Burn patience LOUDLY: a wedged-but-alive peer can hold the
        // control plane for minutes before the descriptive abort, and a
        // silent wait reads as a hang (reference stall-warning cadence,
        // operations.cc:1366-1412, applied to transport waits).
        if (wait_label != nullptr) {
          std::fprintf(stderr,
                       "horovod_tpu: still waiting on %s (idle timeout "
                       "%d/%d before abort)\n",
                       wait_label, idle, max_idle_rounds);
        }
        continue;  // waiting its turn in the relay chain, peer still alive
      }
      return false;
    }
    idle = 0;
    p += got;
    n -= static_cast<size_t>(got);
  }
  return true;
}

bool Socket::SendFrame(const std::vector<uint8_t>& payload) {
  uint64_t len = payload.size();
  if (!SendAll(&len, sizeof(len))) return false;
  if (len == 0) return true;
  return SendAll(payload.data(), payload.size());
}

bool Socket::RecvFrame(std::vector<uint8_t>* payload, int max_idle_rounds,
                       const char* wait_label) {
  uint64_t len = 0;
  if (!RecvAllPatient(&len, sizeof(len), max_idle_rounds, wait_label)) {
    return false;
  }
  if (len > (1ull << 34)) return false;  // 16 GB sanity cap
  payload->resize(len);
  if (len == 0) return true;
  return RecvAll(payload->data(), len);
}

static void SetNoDelay(int fd) {
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
}

// Shared IPv4 resolve (literal first, gethostbyname fallback) for the
// connect paths.  NOTE: gethostbyname is not thread-safe; in this stack
// hosts are near-always IP literals (the peer table carries what workers
// reported), so the fallback only runs on cold non-literal paths.
static bool ResolveIPv4(const std::string& host, in_addr* out,
                        std::string* err) {
  if (::inet_pton(AF_INET, host.c_str(), out) == 1) return true;
  hostent* he = ::gethostbyname(host.c_str());
  if (he == nullptr || he->h_addr_list[0] == nullptr) {
    *err = "cannot resolve host " + host;
    return false;
  }
  memcpy(out, he->h_addr_list[0], sizeof(*out));
  return true;
}

NonblockGuard::NonblockGuard(int fd)
    : fd_(fd), flags_(::fcntl(fd, F_GETFL, 0)) {
  if (flags_ >= 0) ::fcntl(fd_, F_SETFL, flags_ | O_NONBLOCK);
}

NonblockGuard::~NonblockGuard() {
  if (flags_ >= 0) ::fcntl(fd_, F_SETFL, flags_);
}

Socket Listen(const std::string& host, int port, int backlog,
              int* bound_port, std::string* error) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) {
    *error = std::string("socket: ") + strerror(errno);
    return Socket();
  }
  int one = 1;
  ::setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (host.empty() || host == "0.0.0.0") {
    addr.sin_addr.s_addr = INADDR_ANY;
  } else if (!ResolveIPv4(host, &addr.sin_addr, error)) {
    ::close(fd);
    return Socket();
  }
  if (::bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
    *error = std::string("bind: ") + strerror(errno);
    ::close(fd);
    return Socket();
  }
  if (::listen(fd, backlog) != 0) {
    *error = std::string("listen: ") + strerror(errno);
    ::close(fd);
    return Socket();
  }
  if (bound_port != nullptr) {
    sockaddr_in got{};
    socklen_t len = sizeof(got);
    ::getsockname(fd, reinterpret_cast<sockaddr*>(&got), &len);
    *bound_port = ntohs(got.sin_port);
  }
  return Socket(fd);
}

const char* const kAcceptTimedOut =
    "accept: timed out waiting for an incoming connection";

Socket Accept(Socket& listener, std::string* error) {
  // Enforce the listener's SetTimeouts bound with poll(2), NOT the
  // kernel's SO_RCVTIMEO-on-accept behavior: sandboxed/older kernels
  // (e.g. gVisor) silently ignore the latter, which turned every
  // "bounded" rendezvous accept into an unbounded block — the exact
  // half-open-connect wedge this timeout exists to prevent.
  timeval tv{};
  socklen_t tvlen = sizeof(tv);
  int timeout_ms = -1;  // no timeout configured: block indefinitely
  if (::getsockopt(listener.fd(), SOL_SOCKET, SO_RCVTIMEO, &tv, &tvlen) == 0
      && (tv.tv_sec > 0 || tv.tv_usec > 0)) {
    timeout_ms = static_cast<int>(tv.tv_sec * 1000 + tv.tv_usec / 1000);
  }
  // The accept itself runs nonblocking: a connection that poll reported
  // can be reset before accept(2) picks it up (the classic poll/accept
  // race, accept(2) BUGS), and a blocking accept would then wait for the
  // NEXT connection — unbounded, on kernels that ignore SO_RCVTIMEO.
  NonblockGuard nb(listener.fd());
  while (true) {
    pollfd pfd{listener.fd(), POLLIN, 0};
    int rc = ::poll(&pfd, 1, timeout_ms);
    if (rc < 0) {
      if (errno == EINTR) continue;
      *error = std::string("accept poll: ") + strerror(errno);
      return Socket();
    }
    if (rc == 0) {
      // Deadline tick, not a failure — surface it distinctly so
      // rendezvous loops re-check their own deadline instead of
      // mistaking the expiry for a broken listener.
      *error = kAcceptTimedOut;
      return Socket();
    }
    int fd = ::accept(listener.fd(), nullptr, nullptr);
    if (fd >= 0) {
      SetNoDelay(fd);
      return Socket(fd);
    }
    if (errno == EINTR || errno == EAGAIN || errno == EWOULDBLOCK) {
      continue;  // the pending connection vanished (reset before accept)
    }
    *error = std::string("accept: ") + strerror(errno);
    return Socket();
  }
}

bool WaitReadable(Socket& s, int timeout_ms) {
  if (!s.valid()) return false;
  pollfd pfd{s.fd(), POLLIN, 0};
  return ::poll(&pfd, 1, timeout_ms) > 0 && (pfd.revents & POLLIN) != 0;
}

bool HasPendingConnection(Socket& listener) {
  return WaitReadable(listener, 0);
}

Socket TryAcceptNow(Socket& listener) {
  if (!listener.valid() || !HasPendingConnection(listener)) return Socket();
  // The listener goes PERMANENTLY nonblocking on first use: several
  // channel drivers call this concurrently on ONE shared listener, and a
  // save/set/restore guard would race — one driver restoring blocking
  // mode while another sits inside accept(2) on a queue a third just
  // drained re-creates exactly the block-on-empty-queue hazard this
  // function exists to avoid.  The only other accept path (hvd::Accept)
  // already runs its accept nonblocking under poll, so the sticky flag
  // is harmless to it.
  int fl = ::fcntl(listener.fd(), F_GETFL, 0);
  if (fl >= 0 && (fl & O_NONBLOCK) == 0) {
    ::fcntl(listener.fd(), F_SETFL, fl | O_NONBLOCK);
  }
  int fd = ::accept(listener.fd(), nullptr, nullptr);
  if (fd < 0) return Socket();
  SetNoDelay(fd);
  return Socket(fd);
}

Socket ConnectStart(const std::string& host, int port, bool* in_progress,
                    std::string* err) {
  *in_progress = false;
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) {
    *err = std::string("socket: ") + strerror(errno);
    return Socket();
  }
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (!ResolveIPv4(host, &addr.sin_addr, err)) {
    ::close(fd);
    return Socket();
  }
  int fl = ::fcntl(fd, F_GETFL, 0);
  if (fl >= 0) ::fcntl(fd, F_SETFL, fl | O_NONBLOCK);
  if (::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) == 0) {
    // Completed immediately (the loopback common case): hand back a
    // blocking socket like ConnectRetry would.
    SetNoDelay(fd);
    if (fl >= 0) ::fcntl(fd, F_SETFL, fl & ~O_NONBLOCK);
    return Socket(fd);
  }
  if (errno == EINPROGRESS) {
    *in_progress = true;
    return Socket(fd);  // caller polls POLLOUT, then ConnectFinish
  }
  *err = std::string("connect: ") + strerror(errno);
  ::close(fd);
  return Socket();
}

bool ConnectFinish(Socket& s, std::string* err) {
  int soerr = 0;
  socklen_t len = sizeof(soerr);
  if (::getsockopt(s.fd(), SOL_SOCKET, SO_ERROR, &soerr, &len) != 0) {
    soerr = errno;
  }
  if (soerr != 0) {
    *err = std::string("connect: ") + strerror(soerr);
    return false;
  }
  SetNoDelay(s.fd());
  int fl = ::fcntl(s.fd(), F_GETFL, 0);
  if (fl >= 0) ::fcntl(s.fd(), F_SETFL, fl & ~O_NONBLOCK);
  return true;
}

void ArmSocketDeadlines(Socket& s, int deadline_sec) {
  if (!s.valid()) return;
  int one = 1;
  ::setsockopt(s.fd(), SOL_SOCKET, SO_KEEPALIVE, &one, sizeof(one));
  // Probe timing: never SLOWER than the legacy ~30 s detection
  // (idle 10 + 4 x intvl 5), and tightened toward deadline_sec when a
  // smaller bound is in force (fault-capped socket timeouts).
  int idle = 10, intvl = 5, cnt = 4;
  if (deadline_sec > 0) {
    idle = std::max(1, std::min(10, deadline_sec / 3));
    intvl = std::max(1, std::min(5, deadline_sec / 6));
  }
  ::setsockopt(s.fd(), IPPROTO_TCP, TCP_KEEPIDLE, &idle, sizeof(idle));
  ::setsockopt(s.fd(), IPPROTO_TCP, TCP_KEEPINTVL, &intvl, sizeof(intvl));
  ::setsockopt(s.fd(), IPPROTO_TCP, TCP_KEEPCNT, &cnt, sizeof(cnt));
#ifdef TCP_USER_TIMEOUT
  if (deadline_sec > 0) {
    // Unacked transmit data older than this errors the socket (ETIMEDOUT)
    // — converting a "my sends vanish into retransmission limbo" stall
    // into a classifiable error the link-heal layer can act on.  Ignored
    // gracefully by kernels that lack the option (e.g. some sandboxes).
    unsigned to_ms = static_cast<unsigned>(deadline_sec) * 1000u;
    ::setsockopt(s.fd(), IPPROTO_TCP, TCP_USER_TIMEOUT, &to_ms,
                 sizeof(to_ms));
  }
#endif
}

Socket ConnectRetry(const std::string& host, int port, int deadline_ms,
                    std::string* error) {
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(deadline_ms);
  std::string last_err;
  while (std::chrono::steady_clock::now() < deadline) {
    int fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) {
      last_err = std::string("socket: ") + strerror(errno);
      break;
    }
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (!ResolveIPv4(host, &addr.sin_addr, error)) {
      ::close(fd);
      return Socket();
    }
    if (::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) == 0) {
      SetNoDelay(fd);
      return Socket(fd);
    }
    last_err = std::string("connect: ") + strerror(errno);
    ::close(fd);
    std::this_thread::sleep_for(std::chrono::milliseconds(20));
  }
  *error = "timed out connecting to " + host + ":" + std::to_string(port) +
           " (" + last_err + ")";
  return Socket();
}

bool SendRecvAll(Socket& snd, const void* send_buf, size_t sn,
                 Socket& rcv, void* recv_buf, size_t rn,
                 int timeout_ms, std::string* err) {
  return SendRecvChunked(snd, send_buf, sn, rcv, recv_buf, rn, /*chunk=*/0,
                         /*on_chunk=*/nullptr, timeout_ms, err);
}

bool SendRecvChunked(Socket& snd, const void* send_buf, size_t sn,
                     Socket& rcv, void* recv_buf, size_t rn, size_t chunk,
                     const std::function<void(size_t, size_t)>& on_chunk,
                     int timeout_ms, std::string* err, int64_t* wire_ns) {
  const char* sp = static_cast<const char*>(send_buf);
  char* rp = static_cast<char*>(recv_buf);
  const size_t rtotal = rn;
  // Receive bytes already handed to on_chunk; the poll loop fires the
  // callback whenever a whole chunk (or the final partial one) is in.
  size_t delivered = 0;
  if (chunk == 0) chunk = rtotal;  // single callback at the end
  auto t0 = std::chrono::steady_clock::now();
  auto deliver_ready = [&] {
    if (!on_chunk) return;
    size_t done = rtotal - rn;
    while (delivered < done &&
           (done - delivered >= chunk || rn == 0)) {
      size_t len = std::min(chunk, done - delivered);
      if (wire_ns != nullptr) {
        auto now = std::chrono::steady_clock::now();
        *wire_ns += std::chrono::duration_cast<std::chrono::nanoseconds>(
                        now - t0)
                        .count();
        on_chunk(delivered, len);
        t0 = std::chrono::steady_clock::now();
      } else {
        on_chunk(delivered, len);
      }
      delivered += len;
    }
  };
  NonblockGuard g1(snd.fd());
  NonblockGuard g2(rcv.fd());
  while (sn > 0 || rn > 0) {
    pollfd fds[2];
    int nfds = 0;
    int si = -1, ri = -1;
    if (sn > 0) {
      fds[nfds] = {snd.fd(), POLLOUT, 0};
      si = nfds++;
    }
    if (rn > 0) {
      fds[nfds] = {rcv.fd(), POLLIN, 0};
      ri = nfds++;
    }
    int rc = ::poll(fds, nfds, timeout_ms > 0 ? timeout_ms : -1);
    if (rc < 0) {
      if (errno == EINTR) continue;
      *err = std::string("poll: ") + strerror(errno);
      return false;
    }
    if (rc == 0) {
      // With both directions pending either neighbor may be the one that
      // stalled; "link" tells TransportError to name both candidates.
      const char* dir = (sn > 0 && rn > 0) ? "link: "
                        : sn > 0          ? "send to peer: "
                                          : "recv from peer: ";
      *err = dir + std::string("no progress for ") +
             std::to_string(timeout_ms / 1000) + "s (peer hung?)";
      return false;
    }
    if (si >= 0 && (fds[si].revents & (POLLOUT | POLLERR | POLLHUP)) != 0) {
      ssize_t k = ::send(snd.fd(), sp, sn, MSG_NOSIGNAL);
      if (k > 0) {
        sp += k;
        sn -= static_cast<size_t>(k);
      } else if (k < 0 && errno != EAGAIN && errno != EWOULDBLOCK &&
                 errno != EINTR) {
        *err = std::string("send to peer: ") + strerror(errno);
        return false;
      }
    }
    if (ri >= 0 && (fds[ri].revents & (POLLIN | POLLERR | POLLHUP)) != 0) {
      ssize_t k = ::recv(rcv.fd(), rp, rn, 0);
      if (k > 0) {
        rp += k;
        rn -= static_cast<size_t>(k);
        deliver_ready();
      } else if (k == 0) {
        *err = "recv from peer: connection closed (peer process exited?)";
        return false;
      } else if (errno != EAGAIN && errno != EWOULDBLOCK && errno != EINTR) {
        *err = std::string("recv from peer: ") + strerror(errno);
        return false;
      }
    }
  }
  if (wire_ns != nullptr) {
    auto now = std::chrono::steady_clock::now();
    *wire_ns +=
        std::chrono::duration_cast<std::chrono::nanoseconds>(now - t0).count();
  }
  return true;
}

}  // namespace hvd
