// Minimal TCP framing layer for the control and data planes.
//
// The reference delegates transport to MPI (MPI_Gather/Gatherv/Bcast for
// control, MPI_Allreduce/Allgatherv/Bcast for data).  The TPU-native
// runtime has no MPI: processes rendezvous at a coordinator address
// (the same model as the JAX distributed runtime) and exchange
// length-prefixed frames over TCP.  TCP_NODELAY is set everywhere —
// the control plane sends many tiny frames per cycle.
#pragma once

#include <cstdint>
#include <string>
#include <vector>

namespace hvd {

class Socket {
 public:
  Socket() : fd_(-1) {}
  explicit Socket(int fd) : fd_(fd) {}
  ~Socket();
  Socket(const Socket&) = delete;
  Socket& operator=(const Socket&) = delete;
  Socket(Socket&& o) noexcept : fd_(o.fd_) { o.fd_ = -1; }
  Socket& operator=(Socket&& o) noexcept;

  bool valid() const { return fd_ >= 0; }
  int fd() const { return fd_; }
  void Close();

  // Blocking helpers; return false on error/EOF.
  bool SendAll(const void* data, size_t n);
  bool RecvAll(void* data, size_t n);

  // Length-prefixed frames (u64 length + payload).
  bool SendFrame(const std::vector<uint8_t>& payload);
  bool RecvFrame(std::vector<uint8_t>* payload);

 private:
  int fd_;
};

// Listen on host:port (port 0 = ephemeral). Returns listening socket and
// fills *bound_port.
Socket Listen(const std::string& host, int port, int backlog,
              int* bound_port, std::string* error);
// Accept one connection (blocking).
Socket Accept(Socket& listener, std::string* error);
// Connect with retry until deadline_ms elapses (peer may not be up yet).
Socket ConnectRetry(const std::string& host, int port, int deadline_ms,
                    std::string* error);

}  // namespace hvd
