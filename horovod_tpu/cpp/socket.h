// Minimal TCP framing layer for the control and data planes.
//
// The reference delegates transport to MPI (MPI_Gather/Gatherv/Bcast for
// control, MPI_Allreduce/Allgatherv/Bcast for data).  The TPU-native
// runtime has no MPI: processes rendezvous at a coordinator address
// (the same model as the JAX distributed runtime) and exchange
// length-prefixed frames over TCP.  TCP_NODELAY is set everywhere —
// the control plane sends many tiny frames per cycle.
#pragma once

#include <cstdint>
#include <functional>
#include <string>
#include <vector>

namespace hvd {

class Socket {
 public:
  Socket() : fd_(-1) {}
  explicit Socket(int fd) : fd_(fd) {}
  ~Socket();
  Socket(const Socket&) = delete;
  Socket& operator=(const Socket&) = delete;
  Socket(Socket&& o) noexcept : fd_(o.fd_) { o.fd_ = -1; }
  Socket& operator=(Socket&& o) noexcept;

  bool valid() const { return fd_ >= 0; }
  int fd() const { return fd_; }
  void Close();

  // Robustness knobs (a hung-but-connected peer must not block forever —
  // the reference's stall story covers negotiation only; transport hangs
  // were invisible).  Timeout 0 = never time out.  Dead-peer detection
  // (keepalive + TCP_USER_TIMEOUT) is armed via ArmSocketDeadlines below.
  void SetTimeouts(int timeout_sec);
  // SO_SNDBUF/SO_RCVBUF for data-plane sockets (HOROVOD_SOCKET_BUF_BYTES).
  // Bigger buffers let the kernel keep the wire busy while userland is in
  // a reduction kernel — the cheap half of wire/compute overlap.  0 = keep
  // the kernel default.
  void SetBufSizes(int bytes);

  // Blocking helpers; return false on error/EOF/timeout.
  bool SendAll(const void* data, size_t n);
  bool RecvAll(void* data, size_t n);

  // RecvAll for store-and-forward waits (broadcast relays, hierarchical
  // chain hops) where zero bytes for a while can mean "upstream hops still
  // in flight", not "peer hung": tolerates up to `max_idle_rounds`
  // consecutive SO_RCVTIMEO expiries before failing; EOF / hard errors
  // still fail immediately.  A non-null `wait_label` names who is being
  // waited for in a stderr warning each idle round, so patience burns
  // visibly instead of reading as a hang.
  bool RecvAllPatient(void* data, size_t n, int max_idle_rounds,
                      const char* wait_label = nullptr);

  // Length-prefixed frames (u64 length + payload).  `max_idle_rounds` > 0
  // tolerates that many SO_RCVTIMEO expiries while waiting for the frame —
  // the control plane must ride out ranks that are legitimately busy
  // executing a long data-plane collective before their next cycle frame.
  bool SendFrame(const std::vector<uint8_t>& payload);
  bool RecvFrame(std::vector<uint8_t>* payload, int max_idle_rounds = 0,
                 const char* wait_label = nullptr);

 private:
  int fd_;
};

// Scoped O_NONBLOCK toggle: poll-multiplexed loops (SendRecvAll, the
// engine's streaming cascade) must not block inside send/recv/accept;
// the blocking mode is restored on destruction so the frame-based
// control plane keeps its simple blocking reads.
class NonblockGuard {
 public:
  explicit NonblockGuard(int fd);
  ~NonblockGuard();
  NonblockGuard(const NonblockGuard&) = delete;
  NonblockGuard& operator=(const NonblockGuard&) = delete;

 private:
  int fd_;
  int flags_;
};

// Full-duplex transfer: send `sn` bytes on `snd` while receiving `rn` bytes
// from `rcv`, multiplexed with poll(2) on nonblocking fds.  This replaces
// the thread-per-send pattern on the ring hot path (2(N-1) thread spawns
// per collective) with zero extra threads.  `timeout_ms` bounds the time
// with NO forward progress on either direction (<=0 = wait forever).  On
// failure fills *err with a message prefixed "send to peer:" or
// "recv from peer:" so the caller can name the guilty neighbor rank.
bool SendRecvAll(Socket& snd, const void* send_buf, size_t sn,
                 Socket& rcv, void* recv_buf, size_t rn,
                 int timeout_ms, std::string* err);

// SendRecvAll with chunk-pipelined receive processing: every time the
// receive side completes another `chunk` bytes (and once more for the
// final partial chunk), `on_chunk(offset, len)` is invoked from the same
// thread BEFORE the poll loop resumes.  While the callback runs (e.g. a
// ReduceInto of chunk k), the kernel keeps draining/filling both socket
// buffers, so wire time overlaps compute time without any extra thread —
// the ring-phase analogue of HierarchicalAllreduce's chunked local chain.
// `chunk == 0` (or >= rn) degenerates to one callback after the full
// receive.  When non-null, `wire_ns` accumulates time spent progressing
// the sockets (poll/send/recv, callback time excluded) so callers can
// split a collective's wall time into wire vs. reduce.
bool SendRecvChunked(Socket& snd, const void* send_buf, size_t sn,
                     Socket& rcv, void* recv_buf, size_t rn, size_t chunk,
                     const std::function<void(size_t, size_t)>& on_chunk,
                     int timeout_ms, std::string* err,
                     int64_t* wire_ns = nullptr);

// Listen on host:port (port 0 = ephemeral). Returns listening socket and
// fills *bound_port.
Socket Listen(const std::string& host, int port, int backlog,
              int* bound_port, std::string* error);
// Accept one connection.  Honors the listener's SetTimeouts bound
// (SO_RCVTIMEO applies to accept(2) on Linux): with a timeout set, an
// accept that sees no completed connection within the bound returns an
// invalid Socket with *error == kAcceptTimedOut — callers loop against
// their own deadline instead of wedging forever on a listener that a
// half-open or never-arriving connect left silent.
Socket Accept(Socket& listener, std::string* error);

// The distinguished Accept timeout error (deadline expiry, not a failure).
extern const char* const kAcceptTimedOut;

// True when the listener has a completed connection ready to accept RIGHT
// NOW (poll with zero timeout) — the coordinator's per-cycle probe for
// elastic mid-run join candidates; never blocks.
bool HasPendingConnection(Socket& listener);

// Accept a connection ONLY if one is ready right now (zero-timeout poll +
// nonblocking accept); invalid Socket otherwise.  The link-heal path's
// accept primitive: several channel drivers poll one shared data listener
// for RESUME re-handshakes, so a driver whose POLLIN lost the accept race
// must get "nothing" immediately, never block on the NEXT connection.
// Side effect: the listener is left PERMANENTLY nonblocking (per-call flag
// save/restore would race between concurrent drivers; hvd::Accept already
// tolerates a nonblocking listener).
Socket TryAcceptNow(Socket& listener);

// Nonblocking connect pair for poll-multiplexed loops (the link-heal
// re-dial must not park a channel driver for a connect timeout).
// ConnectStart resolves + starts the connect: on immediate completion
// returns a ready BLOCKING socket (*in_progress false); on EINPROGRESS
// returns the in-flight nonblocking socket (*in_progress true) — poll it
// for POLLOUT, then call ConnectFinish, which checks SO_ERROR and
// restores blocking mode on success.
Socket ConnectStart(const std::string& host, int port, bool* in_progress,
                    std::string* err);
bool ConnectFinish(Socket& s, std::string* err);

// Kernel-side dead-peer detection bound for a long-lived connection:
// SO_KEEPALIVE with probe timing that detects a dead-but-ESTABLISHED peer
// within ~min(30s, deadline_sec), plus TCP_USER_TIMEOUT = deadline_sec so
// unacknowledged SENT data errors the socket within the same bound (the
// half a silent keepalive cannot cover: keepalive probes only run on an
// idle connection).  deadline_sec <= 0 keeps the legacy ~30 s keepalive
// probing and sets no user timeout.  Shared by data sockets (aligned with
// HOROVOD_SOCKET_TIMEOUT_SEC, itself capped by the fault timeout) and
// control sockets (rendezvous/CTRL conns), so a dead peer surfaces as a
// socket ERROR inside the fault bound instead of only via the
// coordinator's patience.
void ArmSocketDeadlines(Socket& s, int deadline_sec);

// True when `s` becomes readable within timeout_ms (0 = only if readable
// right now).  Bounds a speculative read on a connection that may never
// send anything — e.g. a port scanner hitting the coordinator's listener.
bool WaitReadable(Socket& s, int timeout_ms);
// Connect with retry until deadline_ms elapses (peer may not be up yet).
Socket ConnectRetry(const std::string& host, int port, int deadline_ms,
                    std::string* error);

}  // namespace hvd
