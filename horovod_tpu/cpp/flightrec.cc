#include "flightrec.h"

#include <cstdarg>
#include <cstdio>
#include <cstring>
#include <chrono>
#include <ctime>

#include <fcntl.h>
#include <signal.h>
#include <unistd.h>

namespace hvd {

static int64_t MonoNs() {
  return std::chrono::duration_cast<std::chrono::nanoseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

void FlightRecorder::Configure(int capacity, const std::string& dir,
                               int rank, int64_t epoch,
                               int64_t clock_offset_ns) {
  // Re-Init (elastic recovery) reconfigures identity but keeps the ring
  // and its history: the events leading INTO an abort are exactly what
  // the post-mortem wants, and a fresh epoch is itself recorded by the
  // caller as an "epoch" event.
  rank_ = rank;
  epoch_ = epoch;
  clock_offset_ns_ = clock_offset_ns;
  std::snprintf(dir_, sizeof(dir_), "%s", dir.c_str());
  if (ring_ == nullptr && capacity > 0) {
    if (capacity > (1 << 16)) capacity = 1 << 16;
    ring_ = new Event[capacity];
    capacity_ = capacity;
  }
}

FlightRecorder::~FlightRecorder() { delete[] ring_; }

void FlightRecorder::Record(const char* kind, int64_t cycle,
                            const char* fmt, ...) {
  if (capacity_ <= 0) return;
  // The recorder is effectively single-writer (the background thread);
  // the spin guard only defends against a racing manual dump.
  while (lock_.test_and_set(std::memory_order_acquire)) {
  }
  int64_t seq = seq_.fetch_add(1);
  Event& e = ring_[seq % capacity_];
  e.seq = seq;
  e.mono_ns = MonoNs();
  e.cycle = cycle;
  std::snprintf(e.kind, sizeof(e.kind), "%s", kind);
  va_list ap;
  va_start(ap, fmt);
  std::vsnprintf(e.text, sizeof(e.text), fmt, ap);
  va_end(ap);
  // JSON-proof the text in place: the dump path must not allocate, so
  // escaping happens at record time (quotes/backslashes/control chars
  // become spaces — forensics text, not payload).
  for (char* p = e.text; *p; ++p) {
    if (*p == '"' || *p == '\\' || static_cast<unsigned char>(*p) < 0x20) {
      *p = ' ';
    }
  }
  lock_.clear(std::memory_order_release);
}

int FlightRecorder::Dump(const char* reason, bool signal_safe) {
  if (capacity_ <= 0 || dir_[0] == '\0') return -1;
  if (!signal_safe) {
    while (lock_.test_and_set(std::memory_order_acquire)) {
    }
  }
  char path[320], tmp[336];
  std::snprintf(path, sizeof(path), "%s/flightrec.rank%d.json", dir_,
                rank_);
  std::snprintf(tmp, sizeof(tmp), "%s.tmp", path);
  int fd = ::open(tmp, O_WRONLY | O_CREAT | O_TRUNC, 0644);
  if (fd < 0) {
    if (!signal_safe) lock_.clear(std::memory_order_release);
    return -1;
  }
  char buf[512];
  char esc_reason[256];
  std::snprintf(esc_reason, sizeof(esc_reason), "%s",
                reason ? reason : "");
  for (char* p = esc_reason; *p; ++p) {
    if (*p == '"' || *p == '\\' || static_cast<unsigned char>(*p) < 0x20) {
      *p = ' ';
    }
  }
  int n = std::snprintf(
      buf, sizeof(buf),
      "{\"rank\": %d, \"epoch\": %lld, \"clock_offset_ns\": %lld, "
      "\"dump_mono_ns\": %lld, \"dump_unix_sec\": %lld, "
      "\"reason\": \"%s\", \"events\": [\n",
      rank_, static_cast<long long>(epoch_),
      static_cast<long long>(clock_offset_ns_),
      static_cast<long long>(MonoNs()),
      static_cast<long long>(::time(nullptr)), esc_reason);
  (void)!::write(fd, buf, n);
  const int64_t seq = seq_.load();
  const int64_t count = seq < capacity_ ? seq : capacity_;
  const int64_t first = seq - count;
  for (int64_t s = first; s < seq; ++s) {
    const Event& e = ring_[s % capacity_];
    n = std::snprintf(
        buf, sizeof(buf),
        "{\"seq\": %lld, \"mono_ns\": %lld, \"cycle\": %lld, "
        "\"kind\": \"%s\", \"text\": \"%s\"}%s\n",
        static_cast<long long>(e.seq), static_cast<long long>(e.mono_ns),
        static_cast<long long>(e.cycle), e.kind, e.text,
        s + 1 < seq ? "," : "");
    (void)!::write(fd, buf, n);
  }
  (void)!::write(fd, "]}\n", 3);
  ::close(fd);
  int rc = ::rename(tmp, path);
  dumps_.fetch_add(1);
  if (!signal_safe) lock_.clear(std::memory_order_release);
  return rc == 0 ? 0 : -1;
}

FlightRecorder& GlobalFlightRecorder() {
  static FlightRecorder* rec = new FlightRecorder();
  return *rec;
}

static void FlightSignalHandler(int sig) {
  // Best-effort crash dump: only open/write/rename after snprintf
  // formatting (practically safe; a crash here loses nothing the crash
  // itself wasn't already losing), then re-raise the default action so
  // exit codes and core dumps behave exactly as without the handler.
  const char* name = sig == SIGSEGV ? "SIGSEGV"
                     : sig == SIGBUS ? "SIGBUS"
                     : sig == SIGFPE ? "SIGFPE"
                     : sig == SIGABRT ? "SIGABRT"
                     : sig == SIGTERM ? "SIGTERM"
                                      : "signal";
  char reason[64];
  std::snprintf(reason, sizeof(reason), "fatal signal %s", name);
  GlobalFlightRecorder().Dump(reason, /*signal_safe=*/true);
  ::signal(sig, SIG_DFL);
  ::raise(sig);
}

void InstallFlightSignalHandlers() {
  static bool installed = false;
  if (installed) return;
  installed = true;
  struct sigaction sa;
  std::memset(&sa, 0, sizeof(sa));
  sa.sa_handler = FlightSignalHandler;
  sigemptyset(&sa.sa_mask);
  sa.sa_flags = SA_RESETHAND;
  for (int sig : {SIGSEGV, SIGBUS, SIGFPE, SIGABRT, SIGTERM}) {
    struct sigaction old;
    std::memset(&old, 0, sizeof(old));
    ::sigaction(sig, nullptr, &old);
    // Never displace a non-default disposition someone else installed
    // (Python's SIGTERM handling, a test harness, faulthandler).
    if (old.sa_handler == SIG_DFL) ::sigaction(sig, &sa, nullptr);
  }
}

}  // namespace hvd
