// Chrome-tracing timeline writer.
//
// Feature parity with the reference Timeline (horovod/common/timeline.{h,cc}
// + docs/timeline.md): rank-0 writes a chrome://tracing JSON stream; each
// tensor is a trace "process" (pid); nested B/E events cover NEGOTIATE and
// execution activities (QUEUE, FUSE, RING_ALLREDUCE, ...); enabled via
// HOROVOD_TIMELINE=<path>.  Thread-safe; flushed once per second.
#pragma once

#include <chrono>
#include <cstdio>
#include <mutex>
#include <string>
#include <unordered_map>

#include "common.h"

namespace hvd {

class Timeline {
 public:
  void Initialize(const std::string& path);
  bool Initialized() const { return file_ != nullptr; }
  // Merged-timeline header: one metadata event carrying the writer's
  // rank, membership epoch, monotonic base of the trace's ts axis, and
  // the rendezvous-estimated clock offset to rank 0 — everything
  // `python -m horovod_tpu.timeline merge` needs to put every rank's
  // events on one aligned time axis.  Re-emitted after a rotation so
  // the newest file stays self-contained.
  void SetMeta(int rank, int64_t epoch, int64_t clock_offset_ns);
  // HOROVOD_TIMELINE_MAX_MB rotation: when the file exceeds this many
  // bytes it is terminated as valid JSON, renamed to "<path>.old"
  // (replacing any previous rotation), and a fresh file (meta header +
  // known pid metadata re-emitted) continues at the same path — the
  // newest events are always in the configured file.  0 = unbounded.
  void SetMaxBytes(int64_t max_bytes) { max_bytes_ = max_bytes; }
  // Flush buffered events now (abort paths: the last cycle before a
  // crash must never be lost to stdio buffering).
  void Flush();
  // Cross-rank flow trace (Dapper-style): the coordinator emits the
  // flow SOURCE ("s") when it commits a negotiation, every executing
  // rank emits the SINK ("f") on its execution span.  The flow id is
  // the string "<name>#<epoch>#<n>" with n a per-name occurrence
  // counter — identical across ranks because every commit executes
  // exactly once on every rank, so the merged trace joins them without
  // any cross-file bookkeeping.
  void FlowSend(const std::string& name, int64_t epoch);
  void FlowRecv(const std::string& name, int64_t epoch);

  void NegotiateStart(const std::string& name);
  void NegotiateRankReady(const std::string& name, int rank);
  void NegotiateEnd(const std::string& name);
  // Negotiation satisfied from the response cache: one instantaneous
  // NEGOTIATE_CACHED marker instead of a NEGOTIATE span — the visual
  // proof that a tensor skipped full coordinator negotiation.
  void NegotiateCached(const std::string& name);
  void Start(const std::string& name);                    // top-level op
  void ActivityStart(const std::string& name, const std::string& activity);
  void ActivityEnd(const std::string& name);
  // Per-channel activity spans: each data-plane channel gets its own
  // trace "thread" (tid) under the tensor's pid, so concurrent channel
  // shards render as parallel tracks instead of corrupting the main
  // track's B/E nesting (tid 0 stays reserved for the op-level spans).
  void ActivityStartCh(const std::string& name, const std::string& activity,
                       int tid);
  void ActivityEndCh(const std::string& name, int tid);
  // Size-based algorithm selection: one instantaneous ALGO_SMALL /
  // ALGO_RING marker per allreduce response, so a trace shows which
  // responses took the latency star vs. the bandwidth ring.
  void Algo(const std::string& name, const char* algo);
  // Backup-worker partial commit: one instantaneous
  // PARTIAL_COMMIT(skipped=...) marker naming the ranks the coordinator
  // left out of this response (straggler forensics on the trace).
  void PartialCommit(const std::string& name, const std::string& skipped);
  // Online-autotuner trials live on one dedicated trace "process"
  // (pid "autotune"): each applied trial writes an instantaneous
  // TUNE_TRIAL(config...) marker plus a span that covers its scoring
  // window — the span ends when the NEXT trial (or the commit) applies,
  // so a trace visually shows which trial's window hurt.  `commit`
  // closes the open span and drops a TUNE_COMMIT marker instead of
  // opening a new window.
  void TuneTrial(const std::string& config, bool commit);
  void End(const std::string& name, DataType dtype, const std::string& shape);

  ~Timeline();

 private:
  int64_t NowUs() const;
  int TensorPid(const std::string& name);
  void WriteEvent(int pid, char phase, const std::string& category,
                  const std::string& op_name = "", int tid = 0);
  void FlushIfDue();
  void WriteMetaHeader();
  void MaybeRotate();
  // fprintf wrapper that feeds the rotation byte counter.
  void Out(const char* fmt, ...) __attribute__((format(printf, 2, 3)));

  FILE* file_ = nullptr;
  std::recursive_mutex mu_;
  bool tune_span_open_ = false;
  std::unordered_map<std::string, int> tensor_pids_;
  std::chrono::steady_clock::time_point start_;
  std::chrono::steady_clock::time_point last_flush_;
  int next_pid_ = 0;
  std::string path_;
  int64_t max_bytes_ = 0;
  int64_t written_ = 0;
  bool meta_set_ = false;
  int meta_rank_ = 0;
  int64_t meta_epoch_ = 0;
  int64_t meta_offset_ns_ = 0;
  // Per-name flow occurrence counters (send side / recv side — rank 0
  // uses both, workers only the recv side).
  std::unordered_map<std::string, int64_t> flow_send_, flow_recv_;
};

}  // namespace hvd
