// The native runtime engine: background coordinator + host data plane.
//
// Functional parity with the reference core (horovod/common/operations.cc):
//   * HorovodGlobalState      → Engine singleton (tensor table, message
//     queue, background thread, fusion buffer, knobs)
//   * BackgroundThreadLoop / RunLoopOnce (operations.cc:1435-1907)
//     → Engine::BackgroundLoop / RunLoopOnce — a lock-step negotiation
//     cycle every HOROVOD_CYCLE_TIME ms (default 5)
//   * rank-0 coordinator protocol (MPI_Gather/v + MPI_Bcast of
//     FlatBuffers lists) → length-prefixed TCP frames to/from the
//     coordinator address (JAX-style rendezvous, no mpirun)
//   * IncrementTensorCount / ConstructMPIResponse (operations.cc:282-517)
//     → MessageTable readiness counting + full cross-rank validation
//   * tensor fusion buffer (operations.cc:149-165, 1815-1842)
//     → same-dtype ready allreduces packed into one ring collective
//   * MPI_Allreduce/Allgatherv/Bcast data plane (operations.cc:1232-1353)
//     → ring allreduce (reduce-scatter + allgather over neighbor TCP
//       sockets — the classic bandwidth-optimal ring the reference gets
//       from NCCL), frame-forwarding ring allgather, pipelined ring
//       broadcast
//   * stall detection (operations.cc:1366-1412) → StallCheck
//   * Timeline hooks (operations.cc:698-710) → timeline.h
//
// The accelerator hot path does NOT go through this engine — jitted SPMD
// programs use XLA collectives over ICI.  This engine serves the host-driven
// paths: eager collectives, the torch frontend, parameter/optimizer
// broadcast, metric averaging, and cross-process (DCN) reductions.
#pragma once

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <deque>
#include <functional>
#include <map>
#include <memory>
#include <mutex>
#include <set>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

#include "common.h"
#include "flightrec.h"
#include "message.h"
#include "shm.h"
#include "socket.h"
#include "timeline.h"

namespace hvd {

// Fixed order of TelemEntry::deltas (the fleet-telemetry counter set).
// Keep in lockstep with horovod_tpu/monitor/metrics.py TELEM_COUNTERS —
// the wire carries positions, not names.
enum TelemCounter {
  TC_DATA_BYTES_TX = 0,
  TC_DATA_BYTES_RX,
  TC_ALLREDUCE_BYTES,
  TC_REDUCESCATTER_BYTES,
  TC_NEGOTIATION_BYTES_TX,
  TC_NEGOTIATION_BYTES_RX,
  TC_CONTROL_ROUND_TRIPS,
  TC_CACHE_HITS,
  TC_CACHE_MISSES,
  TC_TENSORS,
  TC_RESPONSES,
  TC_EXEC_CYCLES,
  TC_SHM_BYTES_TX,
  TC_COMPRESSED_BYTES_TX,
  TC_WIRE_BYTES_SAVED,
  TC_BACKUP_SKIPS,
  TC_STALE_EPOCH_MSGS,
  TC_STALL_WARNINGS,
  TC_PRIORITY_INVERSIONS,
  // Appended entries (PR 20) — the wire carries positions, so new
  // counters only ever go at the END, before TC_COUNT.
  TC_ALLTOALL_BYTES,
  TC_MOE_TOKENS_DROPPED,
  TC_COUNT,
};
extern const char* const kTelemCounterNames[TC_COUNT];

struct TensorTableEntry {
  std::string name;
  RequestType type = RequestType::ALLREDUCE;
  DataType dtype = DataType::FLOAT32;
  TensorShape shape;
  void* data = nullptr;   // caller-owned; in/out for allreduce & broadcast
  int root_rank = -1;
  ReduceOp red_op = ReduceOp::SUM;
  // Resolved wire format this entry was REQUESTED with (global knob or
  // per-tensor override at enqueue time) — part of the cache signature
  // and of any resubmitted Request, so renegotiations keep the format.
  // wire_default marks a knob-derived (advisory) resolution — see
  // Request::wire_default.
  WireDtype wire_dtype = WireDtype::FP32;
  bool wire_default = false;
  // Scheduling priority (0 = most urgent; see Request::priority).
  int32_t priority = 0;
  // Alltoall: this rank's per-destination dim-0 split sizes (see
  // Request::splits).  Empty = legacy equal splits.
  std::vector<int64_t> splits;
  int64_t handle = -1;
  // Enqueue wall-clock: FinishEntry derives the per-collective
  // completion latency (step_time_ns percentiles) from it.
  std::chrono::steady_clock::time_point enqueue_time;
};

struct HandleState {
  std::atomic<int> done{0};   // 0 pending, 1 ok, -1 error
  std::string error;
  // Ranks whose data the committed response actually reduced: size for
  // a full commit, the participant-set size for a backup-worker partial
  // commit, 0 when this rank's entry was skipped — divisor-correct
  // averaging in the frontends divides by THIS, never blindly by size.
  int participants = 0;
  // Allgather result (shape negotiated at runtime, reference
  // operations.cc:796-856): buffered here, copied out by the caller.
  std::vector<uint8_t> result;
  std::vector<int64_t> result_shape;
};

// Small data-plane thread pool (HOROVOD_NUM_CHANNELS workers): drives the
// per-channel ring shards of a sharded collective, executes independent
// responses of one cycle concurrently, and lends idle workers to large
// reductions.  Tasks must be data-plane leaves or channel drivers — the
// only nested use is TrySubmitIfIdle (which never queues behind a busy
// worker), so the pool cannot deadlock on itself.
class DataPool {
 public:
  ~DataPool() { Stop(); }
  void Start(int nthreads);
  void Stop();
  void Submit(std::function<void()> fn);
  // Enqueue only if an idle worker can take the task right now; the
  // caller runs it inline otherwise.  Safe to call from a pool task.
  bool TrySubmitIfIdle(std::function<void()> fn);
  int size() const { return static_cast<int>(threads_.size()); }

 private:
  void Loop();
  std::vector<std::thread> threads_;
  std::deque<std::function<void()>> q_;
  std::mutex mu_;
  std::condition_variable cv_;
  int idle_ = 0;
  bool stop_ = false;
};

// Completion latch for a batch of pool tasks.
class TaskLatch {
 public:
  explicit TaskLatch(int n) : n_(n) {}
  void Done() {
    std::lock_guard<std::mutex> lk(mu_);
    if (--n_ <= 0) cv_.notify_all();
  }
  void Wait() {
    std::unique_lock<std::mutex> lk(mu_);
    cv_.wait(lk, [&] { return n_ <= 0; });
  }

 private:
  std::mutex mu_;
  std::condition_variable cv_;
  int n_;
};

class Engine {
 public:
  static Engine& Get();

  // Returns 0 on success; nonzero + FillLastError on failure.
  int Init(int rank, int size, int local_rank, int local_size,
           const std::string& coordinator_addr);
  void Shutdown();

  bool initialized() const { return initialized_.load(); }
  int rank() const { return rank_; }
  int size() const { return size_; }
  int local_rank() const { return local_rank_; }
  int local_size() const { return local_size_; }
  // Committed membership epoch: bumped by every successful rendezvous
  // commit (first init and every re-init).  Workers adopt the
  // coordinator's value, so all live members of a world agree on it and
  // every control frame carries it (stale frames from a dead incarnation
  // are structurally rejected — see stale_epoch_msgs).
  int64_t epoch() const { return epoch_.load(); }
  const std::string& last_error() const { return last_error_; }

  // Enqueue a collective on caller-owned memory.  Returns a handle, or -1
  // (duplicate name in flight — reference DUPLICATE_NAME_ERROR,
  // operations.cc:2058-2061) or -2 (not initialized / shut down).
  // `probe` marks a dense allreduce as a layout probe (see Request::probe):
  // it completes normally unless peers are gathering the tensor sparsely,
  // in which case the handle fails with the magic "__sparse_retry__:<dim>"
  // error and the caller re-enqueues zero-entry sparse gathers.
  // `wire_dtype` < 0 uses the live global knob (HOROVOD_WIRE_DTYPE /
  // TUNE); >= 0 is a per-tensor override.  Only FLOAT32 allreduces ever
  // wire compressed; everything else is forced to the fp32 wire (i.e.
  // its own dtype's bytes, exactly the pre-compression engine).
  // `priority` (>= 0; 0 = most urgent, the default) is the scheduling
  // priority frontends stamp from registration order — see
  // Request::priority.  `wire_advisory` marks an explicit wire_dtype as
  // knob-like (Request::wire_default): the coordinator commits the first
  // value on a cross-rank disagreement instead of erroring — the seam
  // the statistics-driven wire policy uses, since per-rank gradient
  // stats may legitimately disagree for a step.
  // `splits` (alltoall only): per-destination dim-0 row counts, size_
  // entries summing to shape[0]; empty = legacy equal splits (shape[0]
  // divisible by world size).
  int64_t Enqueue(RequestType type, const std::string& name, DataType dtype,
                  const std::vector<int64_t>& shape, void* data,
                  int root_rank, ReduceOp red_op = ReduceOp::SUM,
                  bool probe = false, int wire_dtype = -1,
                  int priority = 0, bool wire_advisory = false,
                  const std::vector<int64_t>& splits = {});

  // Execution stats (readable from any thread).  `exec_cycles` counts
  // negotiation cycles that executed at least one response on this rank;
  // `responses_executed` counts responses (a fused batch is ONE);
  // `tensors_executed` counts tensors.  tensors/responses > 1 ⇒ fusion;
  // frontends batching N tensors into one cycle see exec_cycles grow by
  // ~1 instead of N (reference async+fusion property,
  // operations.cc:1815-1842).
  int64_t exec_cycles() const { return exec_cycles_.load(); }
  int64_t responses_executed() const { return responses_executed_.load(); }
  int64_t tensors_executed() const { return tensors_executed_.load(); }

  // Response-cache / control-plane observability.  `cache_hits` counts
  // enqueues negotiated as a single slot bit; `cache_misses` counts
  // cacheable-type enqueues that went through full negotiation (first
  // sight of a signature, renegotiation after an evict);
  // `cache_evictions` counts slots dropped from this rank's replica.
  // `negotiation_bytes_tx/rx` sum control-frame payloads (+8-byte length
  // prefix) from this process's perspective; `control_round_trips`
  // counts request→response exchanges that carried NEGOTIATION payload
  // (requests, hit bits, evicts, responses, cached slots, or shutdown —
  // idle heartbeat cycles are excluded) — bench divides it by steps to
  // show the cache collapsing per-tensor negotiation into ~1 round trip
  // per step.
  int64_t cache_hits() const { return cache_hits_.load(); }
  int64_t cache_misses() const { return cache_misses_.load(); }
  int64_t cache_evictions() const { return cache_evictions_.load(); }
  int64_t negotiation_bytes_tx() const { return negotiation_bytes_tx_.load(); }
  int64_t negotiation_bytes_rx() const { return negotiation_bytes_rx_.load(); }
  int64_t control_round_trips() const { return control_round_trips_.load(); }
  // Rendezvous ASSIGN traffic this coordinator sent (frame bytes + the
  // 8-byte length prefix, summed over members and re-rendezvous) — the
  // deterministic counter the scale harness tracks across world sizes.
  int64_t assign_bytes_tx() const { return assign_bytes_tx_.load(); }
  // Control-plane cycle time on the coordinator: wall time from the
  // start of a payload-carrying cycle's frame gathering to the last
  // response send (execution excluded).  p50/p99 over a sliding window
  // of recent cycles, 0 when no sample exists (workers, idle worlds).
  int64_t coordinator_cycle_ns_p50() const {
    return CoordCycleNsPercentile(0.50);
  }
  int64_t coordinator_cycle_ns_p99() const {
    return CoordCycleNsPercentile(0.99);
  }
  // Hierarchical coordination (HOROVOD_HIERARCHICAL_COORDINATOR,
  // committed in the ASSIGN frame): sub-coordinators per host group
  // aggregate readiness so rank 0 handles O(hosts) control frames.
  bool hier_coordinator() const { return hier_coord_; }
  // Control frames dropped because they were stamped with a different
  // membership epoch than this rank's committed one (a delayed message
  // from a dead incarnation after an elastic resize).
  int64_t stale_epoch_msgs() const { return stale_epoch_msgs_.load(); }

  // Data-plane observability.  `data_bytes_tx/rx` sum payload bytes this
  // process moved over ring data sockets (all collective types, all
  // channels); `wire_ns` is cumulative time threads spent progressing
  // data sockets (poll/send/recv) and `reduce_ns` cumulative time inside
  // reduction kernels — both sum ACROSS channels/threads, so either may
  // exceed wall time when channels overlap.  `allreduce_bytes`/
  // `allreduce_ns` sum ring-allreduce payload bytes and wall time; the
  // Python stats() derives allreduce_bus_bw_bytes_per_sec =
  // 2(N-1)/N · bytes / wall from them.  `num_channels` is the COMMITTED
  // per-edge channel count (the coordinator's HOROVOD_NUM_CHANNELS wins
  // at rendezvous so every rank wires the same fan-out).
  int64_t data_bytes_tx() const { return data_bytes_tx_.load(); }
  int64_t data_bytes_rx() const { return data_bytes_rx_.load(); }
  int64_t reduce_ns() const { return reduce_ns_.load(); }
  int64_t wire_ns() const { return wire_ns_.load(); }
  int64_t allreduce_bytes() const { return allreduce_bytes_.load(); }
  int64_t allreduce_ns() const { return allreduce_ns_.load(); }
  // Reduce-scatter observability: payload bytes and wall time of
  // REDUCESCATTER responses (the bus-bandwidth convention for RS is
  // (N-1)/N · bytes / wall — half the allreduce numerator, matching its
  // wire pattern), plus how many responses had to take the exact-parity
  // FALLBACK (full allreduce + local slice: unaligned multi-dim shard
  // geometry or a block-quantized wire) instead of the half-cascade.
  int64_t reducescatter_bytes() const { return reducescatter_bytes_.load(); }
  int64_t reducescatter_ns() const { return reducescatter_ns_.load(); }
  int64_t reducescatter_fallback_count() const {
    return reducescatter_fallback_count_.load();
  }
  // Alltoall observability: payload bytes (full input buffer per
  // response — what the variable-split ring circulates scales it by
  // (N-1)/N, which is also the alltoall busbw numerator convention) and
  // cumulative wall time of ALLTOALL responses.
  int64_t alltoall_bytes() const { return alltoall_bytes_.load(); }
  int64_t alltoall_ns() const { return alltoall_ns_.load(); }
  // MoE plane accounting (runtime/moe.py): cumulative tokens dropped by
  // capacity-factor truncation, noted per dispatch from Python so the
  // counter rides the TELEM fleet aggregation like sharded_steps.
  int64_t moe_tokens_dropped() const { return moe_tokens_dropped_.load(); }
  void NoteMoeDispatch(int64_t dropped) {
    moe_tokens_dropped_.fetch_add(dropped);
  }
  // Sharded-optimizer steps (ZeRO-1: reducescatter(grads) → shard-local
  // update → allgather) completed by the Python frontends on this
  // process — noted like local_sgd_syncs, cumulative.
  int64_t sharded_steps() const { return sharded_steps_.load(); }
  void NoteShardedStep() { sharded_steps_.fetch_add(1); }
  int num_channels() const { return num_channels_; }

  // Shared-memory / hierarchy observability.  `shm_bytes_tx/rx` sum
  // payload bytes this process moved through shm rings (they also count
  // into data_bytes_tx/rx — shm is a transport of the same data plane);
  // `intra_host_bytes` sums payload exchanged with co-located ranks
  // (tx + rx); `algo_small_count/algo_ring_count` count allreduce
  // responses executed via the latency-optimized star path vs. the
  // bandwidth-optimized ring; `topology_hosts` × per-host group sizes is
  // the committed host grouping (this rank reports its own group's size).
  int64_t shm_bytes_tx() const { return shm_bytes_tx_.load(); }
  int64_t shm_bytes_rx() const { return shm_bytes_rx_.load(); }
  int64_t intra_host_bytes() const { return intra_host_bytes_.load(); }
  int64_t algo_small_count() const { return algo_small_count_.load(); }
  int64_t algo_ring_count() const { return algo_ring_count_.load(); }
  int topology_hosts() const { return nnodes_; }
  int topology_local_ranks() const { return group_size_; }
  bool shm_enabled() const { return shm_enabled_; }
  int64_t algo_threshold() const { return algo_threshold_.load(); }

  // Wire-compression observability.  `wire_bytes_saved` sums, per
  // compressed allreduce response, logical payload bytes minus
  // wire-representation bytes (buffer-level: how much smaller the wire
  // format is; ring traffic scales it by ~2(N-1)/N).
  // `compressed_bytes_tx` sums ring payload bytes this rank sent in a
  // compressed wire format; `quantize_ns` is cumulative thread-time in
  // the (de)quantization kernels; the per-mode counters count allreduce
  // RESPONSES executed under each wire format.
  int64_t wire_bytes_saved() const { return wire_bytes_saved_.load(); }
  int64_t compressed_bytes_tx() const { return compressed_bytes_tx_.load(); }
  int64_t quantize_ns() const { return quantize_ns_.load(); }
  int64_t wire_fp16_count() const { return wire_fp16_count_.load(); }
  int64_t wire_bf16_count() const { return wire_bf16_count_.load(); }
  int64_t wire_int8_count() const { return wire_int8_count_.load(); }
  int64_t wire_fp8_count() const { return wire_fp8_count_.load(); }
  // Effective default wire dtype (live-tunable knob #6).
  int wire_dtype() const { return wire_dtype_.load(); }

  // Priority scheduling (HOROVOD_PRIORITY_BANDS, live-tunable knob #7).
  // `priority_bands` is the committed band WIDTH (band = priority /
  // width; 0 = off = bit-identical legacy arrival ordering);
  // `priority_inversions` counts committed responses dispatched after a
  // strictly less-urgent (higher-priority-number) response of the SAME
  // cycle — deterministic (dispatch-list order, not wall clock), and by
  // construction 0 with bands on.  `fusion_ladder(b)` is band b's
  // effective fusion threshold (0 = fall back to the global knob).
  int64_t priority_bands() const { return priority_bands_.load(); }
  int64_t priority_inversions() const {
    return priority_inversions_.load();
  }
  static constexpr int kFusionLadderMax = 8;
  int64_t fusion_ladder(int band) const {
    if (band < 0) return 0;
    if (band >= kFusionLadderMax) band = kFusionLadderMax - 1;
    return fusion_ladder_[band].load();
  }

  // Straggler-tolerance observability.  `backup_workers` is the
  // committed HOROVOD_BACKUP_WORKERS over-provisioning (rendezvous
  // commits the coordinator's value, like the channel count);
  // `backup_skips` counts responses THIS rank was left out of (its
  // entries finished with the clean "skipped this step" status);
  // `local_sgd_syncs` counts outer local-SGD delta syncs the Python
  // policy completed on this process (NoteLocalSgdSync);
  // `step_time_ns_p50/p99` are percentiles of allreduce completion
  // latency (enqueue → finish, successful entries only) over a sliding
  // window — the deterministic per-rank instrument the straggler gate
  // judges: one slow rank inflates every participant's p99 at k=0, and
  // backup-worker commits pull it back down.
  int backup_workers() const { return backup_workers_; }
  // HOROVOD_BACKUP_WORKERS=auto: the coordinator arms k=1 only while
  // the step-time window ratio p99/p50 exceeds
  // HOROVOD_BACKUP_AUTO_RATIO (default 3.0) — a cheap straggler
  // detector on the percentile instrument the straggler gate already
  // trusts.  `backup_auto` reports the mode, `backup_armed` whether the
  // rule currently arms partial commits (coordinator-evaluated; workers
  // report 0 — commits reach them in responses), and the ratio is
  // exported in milli-units so the C ABI stays int64-only.
  bool backup_auto() const { return backup_auto_; }
  int64_t backup_auto_ratio_milli() const {
    return static_cast<int64_t>(backup_auto_ratio_ * 1000.0 + 0.5);
  }
  bool backup_armed() const { return backup_armed_.load(); }
  int64_t backup_skips() const { return backup_skips_.load(); }
  // Link self-healing observability (HOROVOD_LINK_RETRIES /
  // HOROVOD_LINK_HEAL_TIMEOUT_MS).  `link_reconnects` counts data-channel
  // edges transparently re-established mid-collective (each healed edge
  // counts once per endpoint: the sender that re-dialed and the receiver
  // that accepted+ACKed); `link_heal_failures` counts suspects that
  // exhausted the retry/deadline budget and escalated to the unchanged
  // abort path; `link_heal_ns_p50/p99` are sliding-window percentiles of
  // suspect→healed durations on this rank.  All zero under
  // HOROVOD_LINK_RETRIES=0 — the observable proof healing never ran.
  int64_t link_reconnects() const { return link_reconnects_.load(); }
  int64_t link_heal_failures() const { return link_heal_failures_.load(); }
  int64_t link_heal_ns_p50() const { return LinkHealNsPercentile(0.50); }
  int64_t link_heal_ns_p99() const { return LinkHealNsPercentile(0.99); }
  int link_retries() const { return link_retries_; }
  int64_t link_heal_timeout_ms() const { return link_heal_timeout_ms_; }
  int64_t local_sgd_syncs() const { return local_sgd_syncs_.load(); }
  void NoteLocalSgdSync() { local_sgd_syncs_.fetch_add(1); }
  int64_t step_time_ns_p50() const { return StepTimeNsPercentile(0.50); }
  int64_t step_time_ns_p99() const { return StepTimeNsPercentile(0.99); }
  // Participant count recorded on a finished handle (see HandleState).
  int ResultParticipants(int64_t handle);

  // -- fleet observability (HOROVOD_TELEMETRY_CYCLES) --
  // Every `telemetry_cycles` negotiation cycles each rank piggybacks a
  // TELEM entry of counter DELTAS on its RequestList (host leaders sum
  // their group's entries into one per-host entry under hierarchical
  // coordination, so rank 0 still handles O(hosts) telemetry bytes);
  // rank 0 folds the entries into a fleet table readable via FleetJson.
  // 0 disables telemetry entirely — frames are then byte-identical to
  // the pre-telemetry wire (the section is gated on remaining bytes,
  // not a flag).  Final deltas ride the shutdown frame so fleet totals
  // of quiesced counters equal the sum of per-rank stats exactly.
  int64_t telemetry_cycles() const { return telemetry_cycles_; }
  int64_t telem_bytes_tx() const { return telem_bytes_tx_.load(); }
  // Stalled-tensor warnings emitted by this process (coordinator and
  // sub-coordinator detectors), each also mirrored into the flight
  // recorder — the source of the horovod_stall_warnings_total metric.
  int64_t stall_warnings() const { return stall_warnings_.load(); }
  // Rendezvous-estimated monotonic-clock offset to rank 0 (rank0_now ≈
  // my_now + offset; 0 on rank 0): min-RTT midpoint over the ping
  // exchange folded into the JOIN/ASSIGN handshake.  Recorded in the
  // timeline header so `timeline merge` can align per-rank tracks.
  int64_t clock_offset_ns() const { return clock_offset_ns_; }
  // Coordinator-only quorum-lag percentiles: per committed entry, how
  // long the LAST voter trailed the second-to-last (the "would one
  // backup worker have helped" instrument; HOROVOD_BACKUP_WORKERS=auto
  // arms from it under the default rule).  0 on workers / idle worlds.
  int64_t quorum_lag_ns_p50() const { return QuorumLagNsPercentile(0.50); }
  int64_t quorum_lag_ns_p99() const { return QuorumLagNsPercentile(0.99); }
  // HOROVOD_BACKUP_AUTO_RULE: 0 = quorum (default — arm k=1 while the
  // quorum-lag p50 exceeds the grace window: the median last-voter lag
  // being past the grace means a partial commit would be actionable on
  // a typical step), 1 = steptime (the PR 12 rule on rank 0's own
  // completion-latency window, kept as the documented fallback; it
  // cannot see rank 0 itself straggling).
  int backup_auto_rule() const { return backup_auto_rule_; }
  // Rank 0's fleet table as JSON (rows + totals + slowest-rank
  // attribution + quorum-lag percentiles); "{}" on workers before any
  // telemetry arrived.  Readable from any thread, including after
  // shutdown (post-mortem scrapes).
  std::string FleetJson() const;
  int64_t fleet_rows() const;
  // Manual flight-recorder dump (tests, operator tooling); returns 0 on
  // success, -1 when the recorder is disabled or has no dump dir.
  int FlightDump(const char* reason) {
    return GlobalFlightRecorder().Dump(reason);
  }

  // Effective (currently in-force) values of the live-tunable knobs plus
  // the wiring-time ones, for stats()["config"]: post-TUNE, not the env
  // default — an operator reading stats sees what the engine is actually
  // running with.
  int64_t chunk_bytes() const { return chunk_bytes_.load(); }
  int64_t fusion_threshold() const { return fusion_threshold_.load(); }
  int cycle_time_ms() const { return cycle_time_ms_.load(); }
  int wave_width() const { return wave_width_.load(); }
  int channel_drivers() const { return channel_drivers_; }
  int64_t cache_capacity() const { return cache_capacity_; }
  int socket_buf_bytes() const { return socket_buf_bytes_; }
  // TUNE frames applied on this rank (process-cumulative, like every
  // other counter).  Zero under HOROVOD_AUTOTUNE=0 — the observable
  // proof that the default path never sees a TUNE frame.
  int64_t tune_trials() const { return tune_trials_.load(); }

  // Online autotuner entry point (coordinator only, any thread): queue a
  // knob config to broadcast in the next cycle's TUNE frame.  Every rank
  // — the coordinator included — applies it BEFORE that cycle's
  // responses execute, i.e. atomically between negotiation cycles (no
  // response in flight, and no completion-woken enqueue can read a
  // stale knob a peer already flipped); the frame
  // carries the membership epoch, so a TUNE from a dead incarnation is
  // structurally dropped.  Values <= 0 leave the knob unchanged;
  // `commit` marks the search's final config (timeline/observability).
  // Returns 0 queued, -1 when not initialized or not the coordinator.
  // `priority_bands` < 0 leaves the band width unchanged (0 is real:
  // bands off); `fusion_ladder` entries <= 0 leave that band's fusion
  // threshold unchanged (empty ladder = whole ladder unchanged).
  int QueueTune(int64_t chunk_bytes, int64_t fusion_threshold,
                int64_t cycle_time_ms, int64_t wave_width,
                int64_t algo_threshold, int64_t wire_dtype,
                int64_t priority_bands,
                const std::vector<int64_t>& fusion_ladder, bool commit);

  // Why the engine aborted ("" while healthy or after a clean shutdown).
  // Safe to call from any thread: the background thread publishes
  // abort_reason_ before its shut_down_ release-store, and this reads it
  // only after observing shut_down_.
  std::string AbortReason() const;

  int Poll(int64_t handle);                  // 0 pending, 1 ok, -1 error
  int Wait(int64_t handle);                  // blocks; returns Poll result
  std::string ErrorMessage(int64_t handle);
  int64_t ResultNumDims(int64_t handle);
  int64_t ResultDim(int64_t handle, int i);
  int64_t ResultByteSize(int64_t handle);
  int CopyResult(int64_t handle, void* dst, int64_t nbytes);
  void ReleaseHandle(int64_t handle);

 private:
  Engine() = default;
  void BackgroundLoop();
  bool RunLoopOnce();                        // returns false on shutdown
  // Coordinator-led membership rendezvous (worker id 0).  First init
  // requires the full world; an elastic re-init (HOROVOD_ELASTIC=1 and a
  // previously committed epoch) waits a bounded grace window
  // (HOROVOD_ELASTIC_GROW_TIMEOUT_SEC) for relaunched/new candidates,
  // then commits whoever showed up — contiguous re-ranking sorted by
  // persistent worker id, new size, epoch+1 — or fails with a clean
  // terminal error when the survivor count is below
  // HOROVOD_ELASTIC_MIN_SIZE.  Fills the committed peer tables for ring
  // wiring; returns nonzero + last_error_ on failure.
  int CoordinatorRendezvous(const std::string& host, int port,
                            const std::string& my_host, int data_port,
                            std::vector<std::string>* peer_hosts,
                            std::vector<int>* peer_ports);
  // Worker side: join (persistent worker id = the launch-time rank), wait
  // for the ASSIGN frame, adopt (epoch, rank, size) and the peer table.
  int WorkerRendezvous(const std::string& host, int port,
                       const std::string& my_host, int data_port,
                       std::vector<std::string>* peer_hosts,
                       std::vector<int>* peer_ports);
  // Coordinator, elastic mode, once per cycle: zero-timeout probe of the
  // control listener for a join candidate (a relaunched or new worker).
  // A valid join triggers a collective abort so every member re-enters
  // rendezvous and the candidate is admitted under epoch+1; returns true
  // when the cycle loop must exit for that re-rendezvous.
  bool PollJoinCandidate();
  // -- hierarchical coordination (control-plane two-level tree) --
  // Active when the committed HOROVOD_HIERARCHICAL_COORDINATOR flag is
  // set AND the committed topology has >1 host group with >O(hosts)
  // ranks: each group's leader (lowest committed rank) aggregates its
  // members' per-cycle frames into ONE frame toward rank 0, and relays
  // rank 0's response frame back down verbatim — rank 0 exchanges
  // O(hosts) control frames per cycle instead of O(ranks).
  bool HierActive() const { return hier_coord_ && size_ > 1; }
  bool IsGroupLeader() const { return local_index_ == 0; }
  // Epoch-gated control-frame read shared by every gather point (rank 0
  // reading leaders, leaders reading members, workers reading relays):
  // drops + counts frames stamped with a stale membership epoch, bounded
  // so a peer stuck in the past cannot spin the receiver forever.
  // Returns false on transport failure / corrupt frame / stale flood,
  // with *what set to a short reason.
  bool RecvRequestListGated(Socket& conn, int patience, const char* who,
                            RequestList* out, std::string* what);
  // Leader side of one hierarchical cycle: drain the local queue, gather
  // one frame from every group member (epoch-gated), merge — member
  // requests forwarded verbatim (they carry request_rank), member hit
  // bits accumulated in sub_slot_bits_ and forwarded only once the WHOLE
  // group is ready on a slot, evicts unioned, shutdown ORed.  A member
  // transport failure does not fail the cycle: it is reported in the
  // aggregate's fail_rank/fail_message so rank 0 broadcasts the abort
  // naming the member.
  void AggregateGroup(RequestList* agg);
  // Leader → members: relay a raw response frame (identical bytes, so
  // members parse exactly what rank 0 serialized, abort verdicts and
  // TUNE payloads included).  Returns false when a member send failed.
  bool RelayToMembers(const std::vector<uint8_t>& frame);
  // Leader's own failure path: synthesize an abort ResponseList to the
  // members (they are blocked on the relay) before this leader's loop
  // exits — the sub-coordinator analogue of BroadcastAbort.
  void RelayAbortToMembers(const std::string& message);
  // Record one payload cycle's control-plane wall time (rank 0).
  void RecordCoordCycleNs(int64_t ns);
  int64_t CoordCycleNsPercentile(double p) const;
  // Pop the message queue into `my_list`, classifying each request
  // against the local cache replica: known signature → hit bit, changed
  // signature → evict + full request, unknown → full request.  Also
  // flushes requests forced back to full negotiation by a remote evict.
  void DrainMessageQueue(RequestList* my_list);
  // Worker-side replica maintenance for one response frame: apply
  // evict_slots (resubmitting any of our tensors that were riding an
  // evicted slot), then insert new slot assignments carried by the
  // responses.  Must run BEFORE the responses execute (execution drains
  // the tensor table the signatures are read from).
  void ApplyCacheUpdates(const ResponseList& list);
  // Build (but do not execute) the cycle's agreed cached slots from the
  // local replica: replayed single-tensor responses with participants
  // grafted for partial slots, fused like freshly negotiated responses
  // (band-aware under priority bands).  Returns false — aborting the
  // engine — on a replica/protocol inconsistency (an agreed slot this
  // rank does not hold), which would otherwise strand tensors forever.
  bool BuildCachedResponses(const ResponseList& list,
                            std::vector<Response>* out);
  // One cycle's full dispatch (fresh + cached): legacy fresh-then-cached
  // order with bands off, one merged (priority, name)-ordered dispatch
  // with bands on.  Sets *executed_any; returns false on a replica
  // protocol error (engine aborts).
  bool DispatchCycleResponses(ResponseList& list, bool* executed_any);
  // Coordinator-side: drop a slot everywhere (idempotent within a cycle).
  void CoordinatorEvictSlot(uint32_t slot, ResponseList* out);
  void ClearCacheState();
  // -- backup-worker straggler tolerance (HOROVOD_BACKUP_WORKERS=k) --
  // Coordinator, end of every gather cycle under k > 0: commit any SUM
  // allreduce (full-request pending entry or cached-slot readiness)
  // whose ready voter count reached nvoters-k and whose first sighting
  // is older than the grace window — the committed participant set
  // (flat: the seen ranks; hierarchical: every rank of each FULLY-seen
  // host group, a late host being one late voter) rides the response /
  // partial_slots so every rank runs the same full-world ring over the
  // same survivors' data.
  void MaybePartialCommits(ResponseList* out);
  // Validate + build a partially committed single-tensor response over
  // `participants` only (all of them seen); erases the pending entry.
  Response BuildPartialResponse(const std::string& name,
                                const std::vector<uint32_t>& participants);
  bool RankInParticipants(const std::vector<uint32_t>& parts) const;
  // A committed response left THIS rank out: finish any held entries
  // with the clean "skipped this step" status (purging their queued
  // requests so the coordinator never sees a stale late request), bank
  // skip tokens for tensors not yet enqueued, and drop consumed pending
  // hit bits.  Counted once per skipped response in backup_skips.
  void NoteSkippedResponse(const Response& response,
                           std::vector<TensorTableEntry>& entries);
  void RecordStepTimeNs(int64_t ns);
  int64_t StepTimeNsPercentile(double p) const;
  // Coordinator-only: tell every still-reachable worker that `culprit`
  // failed, so survivors abort promptly instead of waiting out their own
  // transport timeouts; sets abort_reason_ to `message`.
  void BroadcastAbort(int culprit, const std::string& message);
  ResponseList CoordinatorStep(std::vector<RequestList>& lists);
  Response BuildResponse(const std::string& name);
  void FuseResponses(std::vector<Response>& responses);
  // Which slice of the channel fan-out an execution owns: channels
  // [channel, channel + nchannels).  The serial path passes the full
  // range; a concurrent wave hands each response ONE channel so their
  // wire streams live on disjoint socket pairs.  `channel` also indexes
  // the fusion scratch slot, keeping concurrent fused batches off each
  // other's buffers.
  // Ring identities stamped into the wiring handshake (hello[1]) and the
  // link-heal RESUME frames.
  enum RingId : int32_t {
    RING_GLOBAL = 0, RING_LOCAL = 1, RING_CROSS = 2, RING_CTRL = 3,
  };
  // One channel's duplex transport toward the ring neighbors: exactly one
  // of (TCP sockets, shm edges) is set.  RingSpec bundles a whole ring's
  // identity — who I am on it, how many ranks it has, and its per-channel
  // ports — so the phase/cascade code runs unchanged over the flat TCP
  // ring, the flat shm ring, the intra-host shm ring, and the leader
  // cross-host ring.
  struct RingPort {
    Socket* next = nullptr;      // TCP: send toward ring-next
    Socket* prev = nullptr;      // TCP: recv from ring-prev
    ShmRing* shm_tx = nullptr;   // shm: send toward ring-next
    ShmRing* shm_rx = nullptr;   // shm: recv from ring-prev
    bool is_shm() const { return shm_tx != nullptr; }
  };
  // Block codec for a quantized (int8/fp8) wire: the ring's "element"
  // becomes one BLOCK of ``[fp32 scale][block_elems quantized values]``
  // (block sized to HOROVOD_CHUNK_BYTES worth of fp32 elements, last
  // block zero-padded), so segment arithmetic, channel sharding and the
  // chunk cascade all run unchanged over uniform block_bytes elements —
  // only the reduction kernel swaps to dequantize-combine-requantize
  // through fp32 staging.
  struct WireCodec {
    WireDtype wire = WireDtype::INT8;
    int64_t block_elems = 0;     // fp32 elements per block
    size_t block_bytes = 0;      // 4 (scale) + block_elems quantized bytes
  };
  struct RingSpec {
    int vrank = 0;
    int rsize = 1;
    std::vector<RingPort> ports;       // indexed by global channel id
    const char* span = "RING_CH";      // timeline activity prefix
    // Non-null: payload is block-quantized wire format (see WireCodec) —
    // the phases reduce blocks instead of elements.  `compressed` also
    // covers the fp16/bf16 staging wires (no codec, but the bytes on
    // this spec's ports are compressed payload → compressed_bytes_tx).
    const WireCodec* codec = nullptr;
    bool compressed = false;
    // Link self-healing identity: which RingId this spec's TCP edges
    // belong to, the committed neighbor ranks (reconnect targets via the
    // peer table), and the per-channel cascade stream-sequence counters
    // (both endpoints of an edge count the same deterministic response
    // sequence per channel, so a RESUME's seq identifies the exact
    // in-flight cascade).  ring_id < 0 / null seq = healing not
    // applicable (shm rings).
    int32_t ring_id = -1;
    int next_peer = -1, prev_peer = -1;
    std::vector<int64_t>* seq = nullptr;
  };

  struct ExecCtx {
    int channel = 0;
    int nchannels = 1;
    // Non-null when this response is one slice of a concurrent wave:
    // an allreduce slice writes its wall time here instead of adding it
    // to allreduce_ns_, and ExecuteResponses accounts the MAX across
    // the wave's slices once — thread-summing would inflate
    // allreduce_ns by the concurrency factor, and charging the whole
    // wave's wall would pollute it with co-scheduled non-allreduce
    // responses; either way the derived bus bandwidth would lie.
    int64_t* wave_allreduce_wall_ns = nullptr;
  };
  // Execute one cycle's agreed responses.  Flat-ring worlds with
  // multiple channels run independent responses concurrently in waves of
  // num_channels_ (assignment by list index — identical on every rank,
  // so cross-rank wire order stays deterministic); everything else
  // (C == 1, hierarchical, single response) executes serially with the
  // full channel range.
  void ExecuteResponses(std::vector<Response>& responses);
  void PerformResponse(const Response& response, const ExecCtx& ctx);
  void ExecAllreduce(const Response& response,
                     std::vector<TensorTableEntry>& entries,
                     const ExecCtx& ctx);
  // The allreduce cascade's path selection over a staged buffer
  // (two-level -> star fold -> quantized/channeled flat ring), shared
  // VERBATIM by ExecAllreduce and ExecReducescatter's exact-parity
  // fallback — one selection, so the fallback's bitwise anchor
  // (reducescatter == allreduce sliced) can never drift from the real
  // allreduce's path choice.  `small` is the caller-evaluated
  // UseSmallAlgo verdict (it depends on the staged byte count);
  // `op_label` names the collective in transport errors.
  bool RunAllreduceCascade(uint8_t* exec_buf, int64_t total,
                           DataType exec_dtype, ReduceOp op,
                           WireDtype wire, bool quantized, bool half_wire,
                           bool small, const char* op_label,
                           const std::string& tname, const ExecCtx& ctx,
                           std::string* msg);
  void ExecAllgather(const Response& response,
                     std::vector<TensorTableEntry>& entries,
                     const ExecCtx& ctx);
  void ExecBroadcast(const Response& response,
                     std::vector<TensorTableEntry>& entries,
                     const ExecCtx& ctx);
  void ExecReducescatter(const Response& response,
                         std::vector<TensorTableEntry>& entries,
                         const ExecCtx& ctx);
  void ExecAlltoall(const Response& response,
                    std::vector<TensorTableEntry>& entries,
                    const ExecCtx& ctx);
  // Ring allreduce sharded across the ctx's channels of the given ring
  // (flat TCP, flat shm, intra-host shm, or the leader cross ring).
  // Channel shards slice WITHIN each ring segment (never re-segment the
  // raw element range), so an element's segment id — and therefore the
  // rank order its reduction applies in — is independent of the channel
  // count AND the transport: results are bit-identical for any fan-out,
  // 1..N, shm or TCP.
  // `rs_only` stops the cascade after the reduce-scatter half: with the
  // caller's spec.vrank pre-rotated by -1, this rank ends owning ring
  // segment `vrank+1` fully reduced — bits identical to the full
  // allreduce's value of that segment (the allgather half moves bytes
  // verbatim, it never changes them).
  bool ChanneledRingAllreduce(uint8_t* base, int64_t count, DataType dtype,
                              ReduceOp op, const RingSpec& spec,
                              const ExecCtx& ctx, const std::string& tname,
                              std::string* err, bool rs_only = false);
  // One channel's chunk-pipelined ring phases over explicit per-segment
  // counts/offsets (absolute element offsets into `base`).
  bool RingReduceScatterPhaseCh(uint8_t* base,
                                const std::vector<int64_t>& seg_count,
                                const std::vector<int64_t>& seg_off,
                                DataType dtype, ReduceOp op,
                                const RingSpec& spec, int ch,
                                std::string* err);
  bool RingAllgatherPhaseCh(uint8_t* base,
                            const std::vector<int64_t>& seg_count,
                            const std::vector<int64_t>& seg_off,
                            size_t esize, const RingSpec& spec, int ch,
                            std::string* err);
  // A set of channels' ENTIRE allreduces (reduce-scatter + allgather),
  // each a chunk-granular streaming cascade, multiplexed in ONE poll
  // loop: the send of chunk k at step s+1 becomes eligible the moment
  // chunk k of step s is received (and, in the reduce-scatter half,
  // reduced) — no per-step barrier anywhere, so a scheduling hiccup on
  // one rank costs one chunk of pipeline depth, not a whole segment
  // round — and one driver thread services whichever channel has work,
  // so channel fan-out never forces thread fan-out (decisive on small
  // hosts; big hosts split channels across pool drivers).  Values are
  // bit-identical to the stepped phases: same segments, same reduction
  // order per element; chunk edges only change WHEN a reduction runs,
  // never what it computes.  Per-channel segment tables are indexed
  // [channel][segment] with absolute element offsets into `base`.
  struct ChannelSegs {
    int ch = 0;  // global channel id (port index in the spec)
    std::vector<int64_t> seg_count, seg_off;
  };
  bool StreamingRingChannels(uint8_t* base,
                             const std::vector<ChannelSegs>& channels,
                             DataType dtype, ReduceOp op,
                             const RingSpec& spec, const std::string& tname,
                             std::string* err, bool rs_only = false);
  // Star-shaped shard delivery down the shm star: the leader (group
  // position 0), holding the fully reduced buffer, sends each member
  // exactly its owned slice [shard_off[m], shard_off[m]+shard_count[m])
  // (absolute element offsets into `base`, indexed by GROUP position) —
  // the scatter twin of StarBroadcast, and lossless by construction, so
  // slicing preserves the fold's bits for ANY shard geometry.
  bool StarScatterShards(uint8_t* base,
                         const std::vector<int64_t>& shard_count,
                         const std::vector<int64_t>& shard_off,
                         size_t esize, std::string* err);
  // Compressed-wire allreduce over `spec`: quantize the fp32 payload
  // into the wire representation (fp16/bf16 halves, or int8/fp8 scaled
  // blocks), run the SAME channel-sharded streaming ring over the wire
  // buffer, dequantize back.  Deterministic for a fixed world (RNE
  // quantization, fixed ring schedule); per-hop requantization makes it
  // value-lossy by design — convergence tests, not bitwise ones.
  bool CompressedRingAllreduce(uint8_t* base, int64_t count,
                               WireDtype wire, ReduceOp op,
                               RingSpec spec, const ExecCtx& ctx,
                               const std::string& tname, std::string* err);
  // The codec's reduction kernel: dequantize both blocks, combine in
  // fp32, rescale + requantize into dst.  Timed into reduce_ns_.
  void WireReduceBlocksTimed(uint8_t* dst, const uint8_t* src,
                             int64_t nblocks, const WireCodec& codec,
                             ReduceOp op);
  // ReduceInto + reduce_ns accounting; splits reductions at or above
  // max(2 MB, 2x the pipeline chunk) across idle pool workers (disjoint
  // element ranges — bit-equal to serial; pipeline-chunk reduces stay
  // serial because they already overlap the wire).
  void ReduceIntoTimed(void* dst, const void* src, int64_t count,
                       DataType dtype, ReduceOp op);
  // Free the fusion scratch high-water allocations (idle for a while, or
  // teardown); cheap no-op when nothing is held.
  void ReleaseScratch();
  void MaybeReleaseScratch();
  // `participants` < 0 = full world (size_); partial commits pass the
  // committed participant count; skipped entries pass 0.
  void FinishEntry(TensorTableEntry& e, const Status& s,
                   int participants = -1);
  void CheckForStalledTensors();
  void CloseSockets();
  // "rank N disconnected during allreduce of 'x': detail" — maps a
  // SendRecvAll error (prefixed send/recv) to the guilty neighbor rank.
  std::string TransportError(const std::string& op, const std::string& name,
                             const std::string& detail, int next_rank,
                             int prev_rank) const;

  std::shared_ptr<HandleState> GetHandle(int64_t handle);

  // -- identity / lifecycle --
  std::atomic<bool> initialized_{false};
  std::atomic<bool> shut_down_{false};
  std::atomic<bool> shutdown_requested_{false};
  int rank_ = 0, size_ = 1, local_rank_ = 0, local_size_ = 1;
  std::string last_error_;
  std::thread background_;

  // -- knobs (reference operations.h:53-58 env vars) --
  // The four LIVE-TUNABLE knobs (cycle_time_ms_, fusion_threshold_,
  // chunk_bytes_ below, wave_width_ below) are atomics: the online
  // autotuner rewrites them between negotiation cycles (ApplyTune, on
  // the background thread) while API threads read them for
  // stats()["config"].  Execution reads happen-after the apply via the
  // cycle structure (a TUNE lands only when no responses are in
  // flight), so relaxed loads are sufficient everywhere.
  //
  // Upper bound on a negotiation cycle's idle wait, NOT a floor: the
  // background loop waits on cycle_cv_ and wakes immediately when work
  // is enqueued (or shutdown/fault is requested), so single-tensor
  // latency is bounded by the control round trip, not by this knob.
  std::atomic<int> cycle_time_ms_{5};
  // HOROVOD_CACHE_CAPACITY: max live negotiation-cache slots (0 disables
  // the cache entirely — every cycle uses the full-Request path).
  int64_t cache_capacity_ = 1024;
  bool cache_enabled_ = false;               // capacity > 0 && size > 1
  std::atomic<int64_t> fusion_threshold_{64 * 1024 * 1024};
  bool stall_check_disabled_ = false;
  int stall_warning_sec_ = 60;
  // No-progress bound for any single transport operation
  // (HOROVOD_SOCKET_TIMEOUT_SEC; 0 disables).  A hung-but-connected peer
  // fails collectives with a descriptive error instead of blocking forever.
  int socket_timeout_sec_ = 120;
  // Idle-round allowance for control-plane frames, derived from
  // HOROVOD_CONTROL_PATIENCE_SEC (absolute, world-size independent).
  int control_patience_rounds_ = 5;
  // Worker-side allowance while waiting on the coordinator's response
  // frame: strictly MORE than the coordinator's, because the coordinator
  // is the failure detector — when another rank wedges, the coordinator
  // must exhaust its own patience and broadcast the abort (naming the
  // culprit) BEFORE an idle worker gives up and can only self-diagnose a
  // generic "lost the coordinator".
  int worker_patience_rounds_ = 11;
  // HOROVOD_FAULT_TIMEOUT_SEC (0 = off): hard bound on the time between a
  // rank dying/hanging and every survivor's HorovodInternalError.  When
  // set it caps both the per-transfer socket timeout and the control-plane
  // patience, so detection never waits out the (much longer) production
  // defaults.
  int fault_timeout_sec_ = 0;

  // -- elastic membership (HOROVOD_ELASTIC=1) --
  // Persistent launch identity: the rank passed to Init (stable across
  // re-inits and supervisor relaunches) is the worker id; committed ranks
  // are assigned per-epoch by the coordinator, contiguous over survivors.
  int worker_id_ = 0;
  // The job's launch-time world size (the env identity); an elastic
  // commit may set size_ below it (shrink) or back up to it (rejoin).
  int world_size_ = 1;
  bool elastic_enabled_ = false;
  int min_size_ = 1;               // HOROVOD_ELASTIC_MIN_SIZE
  int grow_timeout_sec_ = 30;      // HOROVOD_ELASTIC_GROW_TIMEOUT_SEC
  // First-rendezvous deadline (coordinator full-house wait and a worker's
  // whole join+assign exchange), HOROVOD_RENDEZVOUS_TIMEOUT_SEC.
  int rendezvous_timeout_sec_ = 120;
  // Committed membership epoch; survives re-Init (a process keeps its
  // history across engine incarnations) but NOT process relaunch — a
  // fresh replacement adopts the coordinator's epoch at join.
  std::atomic<int64_t> epoch_{0};

  // -- deterministic fault injection (HOROVOD_FAULT_INJECT=rank:step:kind;
  //    kinds: exit | hang | drop-conn).  Armed at Init when rank matches;
  //    fires on the `step`-th Enqueue on this rank (0-based, counting every
  //    collective).  `exit` dies in the enqueueing thread; `hang` freezes
  //    the background loop (control frames stop, the process stays alive);
  //    `drop-conn` makes the background loop close every connection and
  //    abort locally without any shutdown handshake. --
  // stale-epoch: the worker prefixes its next control frame with a
  // duplicate stamped epoch-1 (a dead incarnation's delayed message) so
  // tests can assert the coordinator's structural rejection path.
  // slow: rank:step:slow:ms — a deterministic enqueue delay in the API
  // thread (the background loop keeps heartbeating: a STRAGGLER, not a
  // wedge).  step may be '*' (every enqueue, recurring) so chaos
  // schedules can make a rank permanently slow without killing it.
  // conn-reset: rank:step:conn-reset[:prev] — the rank SHUTDOWN(2)s one
  // of its own data-channel sockets the next time a streaming cascade has
  // moved bytes (send side by default; `prev` shoots the recv side, which
  // discards buffered inbound bytes — the realistic lost-data case the
  // RESUME rewind must repair).  step '*' with a numeric 4th field K
  // re-arms every K-th enqueue (a deterministic flap schedule).
  // recv-stall: rank:step:recv-stall:ms — the next cascade stops draining
  // one channel for ms (a transient network/scheduling stall, NOT a dead
  // link: progress resumes by itself and healing must not reconnect).
  enum class FaultKind {
    NONE, EXIT, HANG, DROP_CONN, STALE_EPOCH, SLOW, CONN_RESET, RECV_STALL
  };
  FaultKind fault_kind_ = FaultKind::NONE;
  int64_t fault_step_ = -1;     // -2: every step ('*')
  int64_t fault_slow_ms_ = 0;
  int64_t fault_reset_period_ = 1;   // conn-reset '*': every K-th enqueue
  bool fault_reset_prev_ = false;    // shoot the recv-side socket instead
  int64_t fault_stall_len_ms_ = 200;
  // Armed by MaybeInjectFault (API thread), consumed by the next GLOBAL-
  // ring streaming cascade (background/pool thread).
  std::atomic<bool> fault_conn_reset_{false};
  std::atomic<int64_t> fault_stall_ms_{0};
  // Survives re-Init: an injected fault fires once per process, so an
  // in-process elastic recovery (shutdown + init with the env var still
  // set) does not re-fire it on every incarnation.
  bool fault_fired_ = false;
  std::atomic<int64_t> enqueue_count_{0};
  std::atomic<bool> fault_hang_{false};
  std::atomic<bool> fault_drop_{false};
  std::atomic<bool> fault_stale_epoch_{false};
  void MaybeInjectFault();

  // Why the background loop aborted (set by the background thread before
  // RunLoopOnce returns false on a transport failure, read by it right
  // after — single-thread access, no lock needed).
  std::string abort_reason_;

  // -- pending work (guarded by mu_) --
  std::mutex mu_;
  std::unordered_map<std::string, TensorTableEntry> tensor_table_;
  std::deque<Request> message_queue_;
  // Wakes the background loop the moment work arrives (Enqueue) or
  // shutdown/fault is requested; RunLoopOnce waits on it with
  // cycle_time_ms_ as the idle-heartbeat upper bound.
  std::condition_variable cycle_cv_;

  // -- handles --
  std::mutex handle_mu_;
  std::unordered_map<int64_t, std::shared_ptr<HandleState>> handles_;
  std::condition_variable handle_cv_;
  std::atomic<int64_t> next_handle_{0};

  // -- coordinator state (rank 0 only; background-thread-only, NOT mu_) --
  struct PendingInfo {
    std::vector<Request> requests;        // one per reporting rank
    std::vector<bool> seen;               // which ranks reported
    // Per-rank arrival times: partial-commit grace is measured from
    // QUORUM formation (the (nvoters-k)-th voter's arrival), not from
    // the first request — an early-bird rank (e.g. a one-shot
    // straggler catching up ahead of peers sleeping out its skip) must
    // not burn the grace budget for everyone else.
    std::vector<std::chrono::steady_clock::time_point> seen_time;
    int count = 0;
    std::chrono::steady_clock::time_point first_seen;
  };
  // Owned exclusively by the background thread (RunLoopOnce and the
  // functions it calls: CoordinatorStep, BuildResponse,
  // CheckForStalledTensors).  Not guarded by mu_ — never touch it from
  // an API thread; AssertBackgroundThread() makes the invariant
  // self-checking at every access site.
  std::unordered_map<std::string, PendingInfo> message_table_;
  std::atomic<std::thread::id> bg_thread_id_{};
  void AssertBackgroundThread() const;
  std::chrono::steady_clock::time_point last_stall_check_;

  // -- negotiation response cache (background-thread-only, like
  //    message_table_; every access site is AssertBackgroundThread-
  //    checked via its callers).
  //
  // Every rank keeps an identical replica: slot → (signature, the
  // single-tensor Response negotiated for it).  The coordinator is the
  // only writer of slot ASSIGNMENTS (broadcast via Response::cache_slots)
  // and EVICTIONS (ResponseList::evict_slots), so the replicas stay in
  // lockstep with the wire protocol's one-frame-per-cycle cadence. --
  struct CacheSignature {
    RequestType type = RequestType::ALLREDUCE;
    DataType dtype = DataType::FLOAT32;
    int32_t root_rank = -1;
    ReduceOp red_op = ReduceOp::SUM;
    // Wire dtype is part of the signature: a live retune of the wire
    // knob changes new requests' signatures, evicting the slot and
    // renegotiating — a cached response can never replay a stale wire
    // format.
    WireDtype wire_dtype = WireDtype::FP32;
    // Priority is signature-relevant too: a priority change must evict
    // and renegotiate so cached-slot replay always orders (and
    // band-fuses) by the CURRENT priority on every rank.
    int32_t priority = 0;
    std::vector<int64_t> shape;
    // Alltoall split geometry: a split change re-routes bytes, so it
    // must evict and renegotiate exactly like a shape change.
    std::vector<int64_t> splits;
    bool Matches(const Request& q) const {
      return q.type == type && q.dtype == dtype && q.root_rank == root_rank &&
             q.red_op == red_op && q.wire_dtype == wire_dtype &&
             q.priority == priority && q.shape == shape &&
             q.splits == splits;
    }
  };
  struct CacheEntry {
    CacheSignature sig;
    Response response;    // single-tensor, ready to execute/fuse
  };
  std::unordered_map<std::string, uint32_t> cache_by_name_;
  std::unordered_map<uint32_t, CacheEntry> cache_entries_;
  // Slots whose hit bit we sent but whose cached response has not fired
  // yet (tensor still in tensor_table_); on an evict broadcast these
  // convert back to full Requests so nothing strands.
  std::unordered_map<uint32_t, std::string> pending_cache_hits_;
  std::vector<Request> cache_resubmits_;     // forced-full after evicts

  // Coordinator-only readiness bits per slot (the cached analogue of
  // PendingInfo) plus the slot allocator.  Freed slot ids are reused
  // smallest-first so ids stay < capacity and hit bitvectors stay tiny.
  struct SlotPending {
    std::vector<bool> seen;
    // Per-voter arrival times (see PendingInfo::seen_time: quorum-based
    // partial-commit grace).
    std::vector<std::chrono::steady_clock::time_point> seen_time;
    int count = 0;
    std::chrono::steady_clock::time_point first_seen;
  };
  std::unordered_map<uint32_t, SlotPending> coord_slot_bits_;
  std::unordered_map<uint32_t, std::string> coord_slot_names_;
  std::unordered_map<std::string, uint32_t> coord_slot_by_name_;
  std::set<uint32_t> free_slots_;
  uint32_t next_slot_ = 0;

  // -- backup-worker straggler tolerance --
  // Committed over-provisioning: the coordinator's env resolution rides
  // the ASSIGN frame (like the channel count) so stats agree everywhere;
  // the per-cycle participant bitmaps are what actually drive behavior.
  // 0 = fully synchronous, bit-for-bit the pre-backup engine.
  int backup_workers_ = 0;
  // Minimum pending age before a partial commit may fire
  // (HOROVOD_BACKUP_GRACE_MS): sub-cycle enqueue jitter between healthy
  // ranks must never be mistaken for straggling — only a rank late by
  // more than the grace gets skipped.
  int backup_grace_ms_ = 50;
  // HOROVOD_BACKUP_WORKERS=auto: k stays 0 until the coordinator's own
  // step-time window turns pathological (p99 > ratio · p50 with enough
  // samples), then partial commits arm at k=1 for as long as the ratio
  // stays above threshold.  Coordinator-local: workers never need k —
  // every commit decision reaches them inside a response.
  bool backup_auto_ = false;
  double backup_auto_ratio_ = 3.0;
  std::atomic<bool> backup_armed_{false};
  // name → outstanding skip tokens (background-thread-only, like
  // message_table_): a partial commit that excluded this rank BEFORE it
  // enqueued the tensor banks a token here; the future enqueue consumes
  // it and finishes "skipped" locally instead of shipping a stale
  // request the coordinator no longer expects.
  std::unordered_map<std::string, int> skip_tokens_;
  // Sliding window of allreduce completion latencies (enqueue→finish)
  // for the step_time_ns percentiles; own lock — FinishEntry runs on
  // the background thread, readers are API threads.
  mutable std::mutex step_ns_mu_;
  std::vector<int64_t> step_ns_samples_;
  size_t step_ns_next_ = 0;

  // -- fleet telemetry (see the public accessors above) --
  // Per-rank send side (background thread only): cycle cadence counter
  // and the last-sent absolute counter snapshot the deltas derive from.
  // telem_last_ survives re-Init on purpose — deltas stay exact across
  // an elastic recovery because they are differences of process-
  // cumulative counters.
  int64_t telemetry_cycles_ = 50;
  int64_t telem_cycle_count_ = 0;
  int64_t telem_last_[TC_COUNT] = {0};
  std::atomic<int64_t> telem_bytes_tx_{0};
  std::atomic<int64_t> stall_warnings_{0};
  // Attach this rank's TELEM entry to the outgoing RequestList when the
  // cadence (or `force` — the shutdown frame) says so.
  void MaybeAttachTelem(RequestList* list, bool force);
  TelemEntry BuildTelemEntry();
  // Rank-0 fleet table: one row per reporting entry (per rank on the
  // flat control plane, per host group under hierarchical coordination).
  // Own mutex: the background thread absorbs, API/monitor threads read.
  struct FleetRow {
    int32_t nranks = 0;
    int32_t host = 0;
    int64_t counters[TC_COUNT] = {0};
    int64_t step_p50 = 0, step_p99 = 0;
    int32_t slow_rank = -1;
    int64_t slow_p99 = 0;
    int64_t updates = 0;
    int64_t last_update_mono_ns = 0;
  };
  mutable std::mutex fleet_mu_;
  std::map<int32_t, FleetRow> fleet_rows_;
  // Rank-granular quorum-lag attribution (commits whose LAST voter was
  // this rank, and its worst lag).  Separate from fleet_rows_ — rows
  // are per-host under hierarchical coordination while attribution
  // stays per rank.  Guarded by fleet_mu_ with the rows.
  struct QuorumAttr {
    int64_t count = 0;
    int64_t max_ns = 0;
  };
  std::map<int32_t, QuorumAttr> quorum_attr_;
  void FleetAbsorb(const TelemEntry& t);
  // Coordinator quorum-lag window (lag of the last voter behind the
  // second-to-last, per committed entry) + per-rank attribution into
  // the fleet rows.  voter_ranks parallel to voter_times.
  void NoteQuorumLag(
      const std::vector<std::chrono::steady_clock::time_point>& times,
      const std::vector<int>& voter_ranks);
  // Synthetic lag sample recorded when a partial commit fires: the
  // skipped voter trails the quorum by at least the time the quorum has
  // been waiting (>= the grace window by construction).  Keeps the
  // arming window saturated while skips are actively occurring —
  // without it, post-arming entries commit WITHOUT the straggler and
  // stop producing lag samples, so the armed verdict would decay and
  // oscillate on window churn.
  void NoteSkippedQuorumLag(int64_t lag_ns);
  int64_t QuorumLagNsPercentile(double p) const;
  mutable std::mutex quorum_mu_;
  std::vector<int64_t> quorum_lag_samples_;
  size_t quorum_lag_next_ = 0;
  int backup_auto_rule_ = 0;       // 0 = quorum (default), 1 = steptime
  // Rendezvous clock sync + flight recorder plumbing.
  int64_t clock_offset_ns_ = 0;
  int64_t control_cycle_seq_ = 0;  // background thread only
  // Per-tensor stall-warning rate limit + one-shot escalation dump.
  std::unordered_map<std::string,
                     std::chrono::steady_clock::time_point>
      stall_last_warned_;
  bool flight_escalated_ = false;

  // -- hierarchical coordination state --
  // Committed flag (coordinator env resolution broadcast in the ASSIGN
  // frame; active only when the topology has >1 group and >1 rank in
  // some group — see HierActive).  =0 restores the flat rank-0 star
  // bit-for-bit.
  bool hier_coord_ = false;
  // Member ↔ leader control connections, wired next to the data rings
  // with the same (origin, ring=CTRL, channel, epoch) handshake: a
  // member holds ONE conn to its group leader; a leader holds one per
  // member, indexed by group position ([0] = itself, unused).
  Socket leader_conn_;                 // member → group leader
  std::vector<Socket> member_conns_;   // leader side, by group position
  // Leader-held partial readiness per cache slot (background-thread-
  // only, like coord_slot_bits_): seen is indexed by GROUP POSITION;
  // the slot's bit goes up to rank 0 only when count == group_size_.
  // Bits for slots evicted by a relayed response are dropped — a stale
  // held bit forwarded after a slot's reassignment would count a false
  // group grant for the new tensor.
  struct SubSlotPending {
    std::vector<bool> seen;
    int count = 0;
    std::chrono::steady_clock::time_point first_seen;
  };
  std::unordered_map<uint32_t, SubSlotPending> sub_slot_bits_;
  // Leader-side stall warning over the held partial bits: a slot whose
  // group never completes would otherwise stall SILENTLY — the leader
  // forwards nothing, so rank 0's detector has count == 0 and prints
  // nothing.  Named after the missing MEMBER ranks, same cadence as
  // CheckForStalledTensors.
  void CheckForStalledSubBits();
  std::chrono::steady_clock::time_point last_sub_stall_check_;

  // -- network --
  Socket control_listener_;                // rank 0
  std::vector<Socket> worker_conns_;       // rank 0: [size-1] control conns
  Socket coordinator_conn_;                // rank != 0
  // Data-plane neighbors (global ring), one independent socket pair per
  // channel (HOROVOD_NUM_CHANNELS; the committed count is broadcast in
  // the rendezvous ASSIGN so every rank wires the same fan-out, and the
  // channel handshake is epoch-stamped so an elastic re-rendezvous
  // rewires every channel of the new incarnation only).
  std::vector<Socket> ring_next_, ring_prev_;
  Socket data_listener_;

  // -- host topology + shared-memory transport (the second channel kind) --
  //
  // The coordinator groups ranks by HOST KEY at rendezvous (HOROVOD_HOST_KEY
  // override, else hostname#boot-id from the JOIN frame) and broadcasts the
  // grouping in the ASSIGN frame.  Co-located ranks wire mmap ring-buffer
  // edges (shm.h) instead of pushing bytes through the loopback TCP ring:
  //   * single host (or any host group spanning the whole world): the flat
  //     ring allreduce runs over shm edges — same algorithm, same segments,
  //     same fold order as the TCP path, so results are BIT-IDENTICAL with
  //     shm on or off;
  //   * multiple hosts with co-located ranks: collectives go two-level —
  //     intra-host ring reduce-scatter over shm, one leader per host in the
  //     inter-host TCP ring (num_channels_-wide), intra-host broadcast back
  //     (the reference's NCCL-reduce → cross-node MPI → NCCL-broadcast
  //     decomposition, operations.cc:1025-1187, generalized from the eager
  //     HOROVOD_HIERARCHICAL_ALLREDUCE into the native engine).  A
  //     different topology is a different (deterministic) reduction order;
  //     within one topology, transport and channel count never change bits.
  // HOROVOD_SHM_DISABLE=1 (or an unavailable /dev/shm, probed on the
  // coordinator) turns all of this off and restores the flat TCP path
  // exactly; the COMMITTED flag is broadcast so every rank agrees.
  bool shm_enabled_ = true;
  bool two_level_ = false;                 // committed: H > 1 and max L > 1
  int node_id_ = 0, nnodes_ = 1;           // my host group id, host count
  std::vector<int32_t> rank_host_;         // committed group id per rank
  std::vector<int> group_members_;         // my group's ranks, ascending
  std::vector<int> group_leaders_;         // first (lowest) rank per group
  int local_index_ = 0;                    // my index in group_members_
  int group_size_ = 1;
  bool shm_ring_active_ = false;           // intra-group shm edges wired
  std::string shm_prefix_;                 // /dev/shm name prefix (job tag)
  // Derive node_id_/group_members_/leaders from the committed rank_host_.
  void AdoptTopology();
  // Create/attach the group's shm edges (ring rings per channel + star
  // edges to the leader), then unlink-after-map.  Bounded by the
  // rendezvous timeout; a peer death mid-wiring surfaces as a clean
  // init error.
  bool WireShmEdges(std::string* err);
  // Intra-group cyclic ring, one ring per direction per channel:
  // shm_ring_tx_[c] carries my bytes toward ring-next, shm_ring_rx_[c]
  // receives from ring-prev (matching the TCP plane, where collectives
  // only ever send next / recv prev).  shm_star_ holds the duplex edges
  // to the group leader (members: [0] = to-leader; the leader: one per
  // member, indexed by group position, [0] unused) — they carry the
  // small-tensor star algorithm, the two-level segment gather, and the
  // result broadcast.
  std::vector<ShmRing> shm_ring_tx_, shm_ring_rx_;
  std::vector<ShmEdge> shm_star_;
  // Leader-only inter-host ring, one socket pair per channel.
  std::vector<Socket> cross_next_, cross_prev_;
  void CloseShmEdges();
  void CountShmBytes(int64_t tx, int64_t rx);

  RingSpec TcpRingSpec();              // whole world over the TCP ring
  RingSpec ShmRingSpec();              // my host group over shm rings
  RingSpec CrossRingSpec();            // leaders over TCP
  // The flat ring collectives actually run on: the shm ring when one host
  // group spans the whole committed world (and shm is wired), the TCP
  // ring otherwise.  Identical vrank/rsize either way, so transport can
  // never change segment arithmetic — only the bytes' route.
  RingSpec FlatRingSpec();
  // Count payload bytes moved on a port (data_bytes_* always; the shm/
  // intra-host counters when the port is an shm edge; compressed_bytes_tx
  // when the bytes are wire-compressed payload).
  void CountPortBytes(const RingPort& port, int64_t tx, int64_t rx,
                      bool compressed = false);
  // Transport-generic primitives on one ring port (TCP socket pair or shm
  // edge) — the phase/relay code calls these and never branches on the
  // channel kind itself.  `patience_rounds` scales the shm no-progress
  // bound exactly like RecvAllPatient's socket-timeout rounds.
  static bool PortSendRecvChunked(
      const RingPort& port, const void* send_buf, size_t sn, void* recv_buf,
      size_t rn, size_t chunk,
      const std::function<void(size_t, size_t)>& on_chunk, int timeout_ms,
      std::string* err, int64_t* wire_ns);
  bool PortSendAll(const RingPort& port, const void* p, size_t n,
                   std::string* err);
  bool PortRecvAllPatient(const RingPort& port, void* p, size_t n,
                          int patience_rounds, std::string* err);

  // Two-level allreduce over the committed topology (see above): intra
  // ring reduce-scatter (or the star fold under the small-tensor algo) →
  // segment gather to the leader → leader ring across hosts → star
  // broadcast back down.  Deterministic per topology; value-independent
  // of transport, channels, and the algo threshold (the star emulates the
  // ring's exact per-segment fold order).
  // `wire`: INT8/FP8 compress ONLY the leader cross-host ring (the hop
  // that crosses a real network); the intra-host shm phases stay at the
  // buffer's dtype.  fp16/bf16 wires never reach here as `wire` —
  // ExecAllreduce stages the whole collective to a half buffer first
  // and passes `compressed_payload` so the ring phases still account
  // the bytes into compressed_bytes_tx.
  bool TwoLevelAllreduce(uint8_t* base, int64_t count, DataType dtype,
                         ReduceOp op, const std::string& name,
                         const ExecCtx& ctx, WireDtype wire,
                         bool compressed_payload, std::string* err);
  // Two-level REDUCE-SCATTER (the RS half of the hierarchy, used only
  // when the committed shard geometry is host-block-aligned — see
  // ExecReducescatter): the intra-host phase runs VERBATIM from
  // TwoLevelAllreduce (same fold, same bits, leader ends holding the
  // full host sum), the leader cross-host ring stops after its
  // reduce-scatter half (leader h ends owning exactly its members'
  // shard block), and the members get their own shards via
  // StarScatterShards instead of the full star broadcast — cross wire
  // and down-link both halve.  shard_count/off are absolute element
  // offsets of the committed per-RANK shards (world-indexed).
  bool TwoLevelReduceScatter(uint8_t* base, int64_t count, DataType dtype,
                             ReduceOp op,
                             const std::vector<int64_t>& shard_count,
                             const std::vector<int64_t>& shard_off,
                             const std::string& name, const ExecCtx& ctx,
                             bool compressed_payload, std::string* err);
  // Shared intra-host phase of the two-level collectives: host-group
  // reduce (star fold under the small algo, else shm ring RS + segment
  // gather) leaving the LEADER holding the full host sum.  Members'
  // buffers are partially clobbered — the caller owes them a broadcast
  // (allreduce) or their shard (reduce-scatter).
  bool TwoLevelIntraReduce(uint8_t* base, int64_t count, DataType dtype,
                           ReduceOp op, const std::string& name,
                           const ExecCtx& ctx, bool compressed_payload,
                           std::string* err);
  // Star (gather→fold→broadcast) allreduce within the host group: every
  // member ships its buffer to the leader over shm, the leader reproduces
  // the ring reduce-scatter's per-segment fold ORDER exactly (same
  // ReduceInto kernel, same operand order, same EvenSegments boundaries —
  // the algo switch can therefore never change a bit), and — when
  // `broadcast_result` — ships the folded buffer back.  2 shm hops of
  // latency instead of 2(L-1) ring steps: the small-tensor path.
  bool StarFoldAllreduce(uint8_t* base, int64_t count, DataType dtype,
                         ReduceOp op, bool broadcast_result,
                         std::string* err);
  // Leader → members full-buffer broadcast over the star edges (chunked).
  bool StarBroadcast(uint8_t* base, size_t nbytes, std::string* err);
  // Should this allreduce take the star path?  bytes under the live
  // threshold, star edges wired, and the serial execution context (a
  // concurrent wave slice owns one CHANNEL, not the star edges).
  bool UseSmallAlgo(int64_t nbytes, const ExecCtx& ctx) const;

  // -- data plane: channels / pool / chunking knobs --
  // Committed per-edge channel count.  The env default is auto from core
  // count (1 restores the single-socket path exactly); the coordinator's
  // value is broadcast at rendezvous so all ranks agree.
  int num_channels_ = 1;
  // HOROVOD_SOCKET_BUF_BYTES: SO_SNDBUF/SO_RCVBUF for ring data sockets
  // (0 = kernel default).  Bigger buffers keep the wire moving while
  // userland reduces — the kernel-side half of wire/compute overlap.
  int socket_buf_bytes_ = 0;
  // HOROVOD_CHUNK_BYTES: ring-phase pipeline chunk (recv of chunk k+1
  // overlaps the ReduceInto of chunk k); multiple of 8 so chunk edges
  // align to every dtype.  Live-tunable (see the knobs comment above).
  std::atomic<int64_t> chunk_bytes_{1 << 20};
  // HOROVOD_ALGO_THRESHOLD: size-based algorithm selection (the NCCL
  // tree-vs-ring pattern PAPER.md's L0 layer delegates downward).
  // Allreduces at or under this many payload bytes take the
  // latency-optimized star path when star edges are wired; 0 disables.
  // Live-tunable (committed at rendezvous, retuned via TUNE frames —
  // every rank must agree or the wire patterns split).  Value-neutral by
  // construction: the star reproduces the ring's exact fold order.
  std::atomic<int64_t> algo_threshold_{32 * 1024};
  // HOROVOD_WIRE_DTYPE: default wire format for fp32 allreduce payloads
  // (WireDtype values; live-tunable knob #6).  Per-rank agreement comes
  // from negotiation, not from this knob: every Request carries its
  // resolved wire dtype and the coordinator validates cross-rank, so a
  // heterogeneous env surfaces as a clean error — never a garbled wire.
  std::atomic<int> wire_dtype_{0};
  // HOROVOD_PRIORITY_BANDS: priority band WIDTH (band = priority /
  // width).  0 = off: bit-identical legacy arrival ordering, no wave
  // splitting, no band fusion gate.  > 0: the coordinator orders each
  // cycle's responses by (priority, name), fusion only merges within a
  // band, and waves dispatch in band order.  Committed in the
  // rendezvous ASSIGN (ordering IS the wire pattern) and live-tunable
  // thereafter (knob #7).
  std::atomic<int64_t> priority_bands_{0};
  // Per-band fusion-threshold ladder (HOROVOD_FUSION_LADDER env /
  // autotuner-learned): band b's threshold, 0 = fall back to the global
  // fusion_threshold_.  Bands >= kFusionLadderMax share the last slot.
  std::atomic<int64_t> fusion_ladder_[kFusionLadderMax] = {};
  std::atomic<int64_t> priority_inversions_{0};
  // Resolve a response's scheduling priority on THIS rank: the
  // coordinator stamped resp.priority at build time; workers received
  // the committed NONZERO values in the frame's trailing priority
  // section (absence = committed 0 — never the local entry, whose
  // stamp differs on a probing rank).  -1 = unknown (ghost rides,
  // errors, foreign sparse retries).
  int ResolveResponsePriority(Response& resp);
  int64_t ResponseBand(const Response& resp) const {
    const int64_t width = priority_bands_.load();
    if (width <= 0 || resp.priority < 0) return 0;
    return resp.priority / width;
  }
  // Count dispatch-order priority inversions over one cycle's combined
  // execution list (`first` dispatches before `second`) and fold them
  // into priority_inversions_.
  void CountPriorityInversions(const std::vector<Response>& first,
                               const std::vector<Response>& second);
  // Merge this cycle's cached + fresh responses into ONE dispatch list
  // ordered by (priority, first name) — errors/sparse-retries first
  // (they execute locally, no wire), partial commits last (their
  // priority is unknowable on ghost ranks, so the rule must derive from
  // the response alone).  Only used with priority_bands > 0.
  static void OrderResponsesByPriority(std::vector<Response>& responses);
  // HOROVOD_SHM_RING_BYTES: per-direction shm ring capacity.
  int64_t shm_ring_bytes_ = 2 << 20;
  // Concurrent-response wave width: how many independent responses of
  // one cycle execute at once on disjoint channels (<= num_channels_).
  // The committed value is broadcast in the rendezvous ASSIGN next to
  // the channel count — waves pick channels by response index, so a
  // cross-rank mismatch would pair different responses on the same
  // socket.  Live-tunable thereafter (TUNE frames apply on every rank at
  // the same cycle boundary, which preserves the agreement).
  std::atomic<int> wave_width_{1};
  // HOROVOD_CHANNEL_DRIVERS: how many threads actively drive the channel
  // fan-out of ONE collective (default auto: one per core).  Channels
  // above this count are multiplexed within a driver's poll loop, so
  // adding channels never oversubscribes a small host.
  int channel_drivers_ = 1;
  DataPool pool_;

  // -- link self-healing (HOROVOD_LINK_RETRIES > 0) --
  // A data-channel socket failure mid-cascade (reset/EOF/TCP_USER_TIMEOUT)
  // is classified SUSPECT instead of fatal: the channel's cascade parks at
  // its exact step/offset cursor while the edge's sender re-dials the
  // receiver's data listener with a RESUME hello (capped-backoff loop,
  // at most link_retries_ attempts within link_heal_timeout_ms_) and the
  // receiver ACKs its authoritative cursor so the sender rewinds — the
  // collective then completes bit-identically (resent bytes are re-read
  // from the same buffer positions; the pipeline's credit chain
  // guarantees un-received bytes are never overwritten).  Exhaustion
  // escalates to the UNCHANGED abort path with the original transport
  // error (same culprit attribution).  =0 disables healing entirely —
  // behavior is bit-for-bit the pre-heal engine.  Both knobs are the
  // coordinator's resolution, committed in the ASSIGN frame: a
  // heterogeneous env must not leave one endpoint healing an edge the
  // other already abandoned.
  int link_retries_ = 3;
  int64_t link_heal_timeout_ms_ = 10000;
  // Committed peer table (host:port per rank), kept for mid-run
  // reconnects; refreshed by every rendezvous.
  std::vector<std::string> peer_hosts_;
  std::vector<int> peer_ports_;
  // Per-channel cascade stream sequences (GLOBAL ring / leader CROSS
  // ring).  Each StreamingRingChannels invocation bumps its channels'
  // counters; both endpoints of an edge execute the same deterministic
  // response sequence over the same channels, so the counters agree and
  // a RESUME names exactly one in-flight cascade.  Channel-disjoint
  // writers (wave/driver assignment) — no lock needed.
  std::vector<int64_t> link_seq_global_, link_seq_cross_;
  // Resume connections accepted by a cascade that does not own the named
  // channel (another driver's channel, or a cascade not yet entered):
  // parked here for the owner, which ACKs from its own cursor.  Keyed
  // (ring_id, channel); newest wins.
  std::mutex heal_mu_;
  std::map<std::pair<int32_t, int32_t>, std::pair<LinkResume, Socket>>
      heal_inbox_;
  std::atomic<int> heal_inbox_size_{0};
  std::atomic<int64_t> link_reconnects_{0};
  std::atomic<int64_t> link_heal_failures_{0};
  mutable std::mutex heal_ns_mu_;
  std::vector<int64_t> heal_ns_samples_;
  size_t heal_ns_next_ = 0;
  void RecordLinkHealNs(int64_t ns);
  int64_t LinkHealNsPercentile(double p) const;
  // Deposit an accepted RESUME conn for the owning cascade (newest wins).
  void HealInboxPut(int32_t ring, int32_t channel, const LinkResume& lr,
                    Socket conn);
  // Claim a parked RESUME conn for (ring, channel); invalid Socket when
  // none is parked.
  bool HealInboxTake(int32_t ring, int32_t channel, LinkResume* lr,
                     Socket* conn);
  void HealInboxClear();

  // -- fusion scratch (one slot per channel: a concurrent wave gives each
  //    response its own buffer; slot 0 serves the serial path).  Capped
  //    at HOROVOD_FUSION_THRESHOLD and released after a 2 s idle spell or
  //    at teardown, so the high-water allocation is not retained forever. --
  std::vector<std::vector<uint8_t>> fusion_buffers_;
  std::chrono::steady_clock::time_point last_exec_time_;

  // -- online autotune (TUNE broadcast) --
  // Pending proposal queued by QueueTune (API thread) and drained into
  // the next cycle's ResponseList by the coordinator's background loop.
  struct TuneSpec {
    int64_t trial_id = 0;
    int64_t chunk_bytes = 0;
    int64_t fusion_threshold = 0;
    int32_t cycle_time_ms = 0;
    int32_t wave_width = 0;
    int64_t algo_threshold = -1;  // < 0: leave unchanged (0 is a real value)
    int32_t wire_dtype = -1;      // < 0: leave unchanged (0 = fp32 is real)
    int64_t priority_bands = -1;  // < 0: leave unchanged (0 = bands off)
    std::vector<int64_t> fusion_ladder;  // empty: unchanged; <=0 per band
    bool commit = false;
  };
  std::mutex tune_mu_;
  // Atomic so the cycle gate's wait predicate can see a pending TUNE
  // without taking tune_mu_ under mu_ — QueueTune's notify is only
  // effective because the woken predicate re-checks this flag.
  std::atomic<bool> tune_pending_{false};
  TuneSpec pending_tune_;
  std::atomic<int64_t> tune_trial_seq_{0};
  // Coordinator/background-loop side: move the pending proposal (if
  // any) into the cycle's outgoing ResponseList; returns true when the
  // frame now carries a TUNE.
  bool DrainPendingTune(ResponseList* out);
  // Apply a received (or locally drained, size==1) TUNE between cycles:
  // clamp exactly like Init so every rank lands on identical effective
  // values, bump tune_trials_, and record the trial on the timeline.
  void ApplyTune(const ResponseList& list);

  // -- execution stats --
  std::atomic<int64_t> exec_cycles_{0};
  std::atomic<int64_t> responses_executed_{0};
  std::atomic<int64_t> tensors_executed_{0};
  std::atomic<int64_t> cache_hits_{0};
  std::atomic<int64_t> cache_misses_{0};
  std::atomic<int64_t> cache_evictions_{0};
  std::atomic<int64_t> negotiation_bytes_tx_{0};
  std::atomic<int64_t> negotiation_bytes_rx_{0};
  std::atomic<int64_t> control_round_trips_{0};
  std::atomic<int64_t> stale_epoch_msgs_{0};
  std::atomic<int64_t> assign_bytes_tx_{0};
  // Sliding window of coordinator payload-cycle control times (ns) for
  // the p50/p99 getters; guarded by cycle_ns_mu_ (one lock per cycle on
  // rank 0, read by API threads).
  mutable std::mutex cycle_ns_mu_;
  std::vector<int64_t> cycle_ns_samples_;
  size_t cycle_ns_next_ = 0;
  std::atomic<int64_t> data_bytes_tx_{0};
  std::atomic<int64_t> data_bytes_rx_{0};
  std::atomic<int64_t> reduce_ns_{0};
  std::atomic<int64_t> wire_ns_{0};
  std::atomic<int64_t> allreduce_bytes_{0};
  std::atomic<int64_t> allreduce_ns_{0};
  std::atomic<int64_t> reducescatter_bytes_{0};
  std::atomic<int64_t> reducescatter_ns_{0};
  std::atomic<int64_t> reducescatter_fallback_count_{0};
  std::atomic<int64_t> alltoall_bytes_{0};
  std::atomic<int64_t> alltoall_ns_{0};
  std::atomic<int64_t> moe_tokens_dropped_{0};
  std::atomic<int64_t> sharded_steps_{0};
  std::atomic<int64_t> shm_bytes_tx_{0};
  std::atomic<int64_t> shm_bytes_rx_{0};
  std::atomic<int64_t> intra_host_bytes_{0};
  std::atomic<int64_t> algo_small_count_{0};
  std::atomic<int64_t> algo_ring_count_{0};
  std::atomic<int64_t> tune_trials_{0};
  std::atomic<int64_t> wire_bytes_saved_{0};
  std::atomic<int64_t> compressed_bytes_tx_{0};
  std::atomic<int64_t> quantize_ns_{0};
  std::atomic<int64_t> wire_fp16_count_{0};
  std::atomic<int64_t> wire_bf16_count_{0};
  std::atomic<int64_t> wire_int8_count_{0};
  std::atomic<int64_t> wire_fp8_count_{0};
  std::atomic<int64_t> backup_skips_{0};
  std::atomic<int64_t> local_sgd_syncs_{0};

  // -- timeline --
  Timeline timeline_;
};

// Element-wise combine of src into dst (the data-plane reduction kernel):
// sum/min/max/prod.  f16/bf16 combine via float, like the reference custom
// MPI op (horovod/common/half.cc) but TPU-era: bf16 is first-class.
void ReduceInto(void* dst, const void* src, int64_t count, DataType dtype,
                ReduceOp op);

}  // namespace hvd
