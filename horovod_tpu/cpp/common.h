// Core abstractions of the native runtime.
//
// TPU-native rebuild of the reference framework-agnostic seam
// (reference horovod/common/common.h:37-110: Status/TensorShape/Tensor/
// OpContext) — redesigned for a host-driven engine whose data plane is
// CPU buffers handed over a C ABI (ctypes), with the accelerator hot path
// living entirely in XLA.  No framework allocation inversion is needed:
// callers own their buffers; the engine owns fusion scratch.
#pragma once

#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

namespace hvd {

enum class StatusType : uint8_t {
  OK = 0,
  UNKNOWN = 1,
  PRECONDITION = 2,
  ABORTED = 3,
  INVALID_ARGUMENT = 4,
  IN_PROGRESS = 5,
};

class Status {
 public:
  Status() : type_(StatusType::OK) {}
  Status(StatusType type, std::string reason)
      : type_(type), reason_(std::move(reason)) {}
  static Status OK() { return Status(); }
  static Status Unknown(std::string r) {
    return Status(StatusType::UNKNOWN, std::move(r));
  }
  static Status PreconditionError(std::string r) {
    return Status(StatusType::PRECONDITION, std::move(r));
  }
  static Status Aborted(std::string r) {
    return Status(StatusType::ABORTED, std::move(r));
  }
  static Status InvalidArgument(std::string r) {
    return Status(StatusType::INVALID_ARGUMENT, std::move(r));
  }
  static Status InProgress() { return Status(StatusType::IN_PROGRESS, ""); }
  bool ok() const { return type_ == StatusType::OK; }
  bool in_progress() const { return type_ == StatusType::IN_PROGRESS; }
  StatusType type() const { return type_; }
  const std::string& reason() const { return reason_; }

 private:
  StatusType type_;
  std::string reason_;
};

// Wire dtypes (superset of reference mpi_message.h:26-37: adds BFLOAT16,
// the TPU-native reduced precision).
enum class DataType : uint8_t {
  UINT8 = 0,
  INT8 = 1,
  UINT16 = 2,
  INT16 = 3,
  INT32 = 4,
  INT64 = 5,
  FLOAT16 = 6,
  FLOAT32 = 7,
  FLOAT64 = 8,
  BOOL = 9,
  BFLOAT16 = 10,
};

inline size_t DataTypeSize(DataType dt) {
  switch (dt) {
    case DataType::UINT8:
    case DataType::INT8:
    case DataType::BOOL:
      return 1;
    case DataType::UINT16:
    case DataType::INT16:
    case DataType::FLOAT16:
    case DataType::BFLOAT16:
      return 2;
    case DataType::INT32:
    case DataType::FLOAT32:
      return 4;
    case DataType::INT64:
    case DataType::FLOAT64:
      return 8;
  }
  return 0;
}

inline const char* DataTypeName(DataType dt) {
  switch (dt) {
    case DataType::UINT8: return "uint8";
    case DataType::INT8: return "int8";
    case DataType::UINT16: return "uint16";
    case DataType::INT16: return "int16";
    case DataType::INT32: return "int32";
    case DataType::INT64: return "int64";
    case DataType::FLOAT16: return "float16";
    case DataType::FLOAT32: return "float32";
    case DataType::FLOAT64: return "float64";
    case DataType::BOOL: return "bool";
    case DataType::BFLOAT16: return "bfloat16";
  }
  return "?";
}

// Negotiated per-response WIRE format for allreduce payloads
// (HOROVOD_WIRE_DTYPE, overridable per tensor from the frontend).  The
// tensor keeps its own dtype end to end; the wire dtype only governs the
// bytes between ranks: fp16/bf16 wires carry RNE-converted halves, and
// int8/fp8 wires carry per-chunk-scaled quantized blocks
// (``[fp32 scale][block]``, block sized to HOROVOD_CHUNK_BYTES).  FP32
// (the default) is byte-identical to the uncompressed engine.  Applies to
// FLOAT32 allreduce only; every other dtype/op wires at its own format.
enum class WireDtype : uint8_t {
  FP32 = 0,
  FP16 = 1,
  BF16 = 2,
  INT8 = 3,
  FP8 = 4,   // e4m3 with per-chunk scales (saturating, no inf)
};

inline const char* WireDtypeName(WireDtype w) {
  switch (w) {
    case WireDtype::FP32: return "fp32";
    case WireDtype::FP16: return "fp16";
    case WireDtype::BF16: return "bf16";
    case WireDtype::INT8: return "int8";
    case WireDtype::FP8: return "fp8";
  }
  return "?";
}

class TensorShape {
 public:
  void AddDim(int64_t d) { dims_.push_back(d); }
  int ndim() const { return static_cast<int>(dims_.size()); }
  int64_t dim(int i) const { return dims_[i]; }
  const std::vector<int64_t>& dims() const { return dims_; }
  int64_t num_elements() const {
    int64_t n = 1;
    for (auto d : dims_) n *= d;
    return n;
  }
  bool operator==(const TensorShape& o) const { return dims_ == o.dims_; }
  bool operator!=(const TensorShape& o) const { return dims_ != o.dims_; }
  std::string DebugString() const {
    std::string s = "[";
    for (size_t i = 0; i < dims_.size(); ++i) {
      if (i) s += ", ";
      s += std::to_string(dims_[i]);
    }
    return s + "]";
  }

 private:
  std::vector<int64_t> dims_;
};

}  // namespace hvd
