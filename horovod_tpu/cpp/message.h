// Control-plane wire protocol.
//
// Role parity with the reference's FlatBuffers messages
// (horovod/common/mpi_message.{h,cc} + wire/mpi_message.fbs): Request /
// RequestList flow worker→coordinator, Response / ResponseList flow back.
// The encoding here is a deliberately simple length-prefixed binary format
// (no schema compiler, no vendored library): all peers run the same build
// on the same arch, so cross-version schema evolution — FlatBuffers' reason
// to exist — buys nothing for an in-cluster control plane.
#pragma once

#include <cstdint>
#include <string>
#include <vector>

#include "common.h"

namespace hvd {

enum class RequestType : uint8_t {
  ALLREDUCE = 0,
  ALLGATHER = 1,
  BROADCAST = 2,
  // Extensions beyond the reference wire protocol (the reference's eager
  // surface stops at the three ops above); negotiated identically.
  REDUCESCATTER = 3,
  ALLTOALL = 4,
};

enum class ResponseType : uint8_t {
  ALLREDUCE = 0,
  ALLGATHER = 1,
  BROADCAST = 2,
  ERROR = 3,
  REDUCESCATTER = 4,
  ALLTOALL = 5,
  // Sparse-layout rendezvous (no reference equivalent; the reference
  // deadlocks when a torch param produces sparse grads on some ranks and
  // none on others in the same step): tells ranks whose dense LAYOUT-PROBE
  // allreduce conflicts with peers' pending sparse gathers to retry as a
  // zero-entry sparse gather.  tensor_sizes[0] carries the sparse_dim
  // gleaned from the peers' '<name>.idx' request shape.
  SPARSE_RETRY = 6,
};

inline const char* RequestTypeName(RequestType t) {
  switch (t) {
    case RequestType::ALLREDUCE: return "allreduce";
    case RequestType::ALLGATHER: return "allgather";
    case RequestType::BROADCAST: return "broadcast";
    case RequestType::REDUCESCATTER: return "reducescatter";
    case RequestType::ALLTOALL: return "alltoall";
  }
  return "?";
}

// Reduction operator for allreduce/reducescatter.  The reference wire
// protocol is SUM-only (mpi_message.h); MIN/MAX/PROD close the asymmetry
// with the jit path's psum/pmin/pmax/product collectives.
enum class ReduceOp : uint8_t {
  SUM = 0,
  MIN = 1,
  MAX = 2,
  PROD = 3,
};

inline const char* ReduceOpName(ReduceOp op) {
  switch (op) {
    case ReduceOp::SUM: return "sum";
    case ReduceOp::MIN: return "min";
    case ReduceOp::MAX: return "max";
    case ReduceOp::PROD: return "prod";
  }
  return "?";
}

struct Request {
  int32_t request_rank = 0;
  RequestType type = RequestType::ALLREDUCE;
  DataType dtype = DataType::FLOAT32;
  std::string tensor_name;
  int32_t root_rank = -1;   // broadcast only
  ReduceOp red_op = ReduceOp::SUM;  // allreduce/reducescatter only
  // Layout probe: "this rank has no local gradient for this tensor and
  // does not know its layout; these are placeholder zeros."  A probe
  // behaves as a normal dense allreduce participant unless the coordinator
  // sees peers gathering the tensor sparsely, in which case the probing
  // ranks get a SPARSE_RETRY response instead of a deadlock.
  bool probe = false;
  // Requested WIRE format for this tensor's allreduce payload (see
  // common.h WireDtype).  EXPLICIT per-tensor overrides are validated
  // cross-rank exactly like dtype: the coordinator commits ONE wire
  // format per response and a mismatch between overrides is a clean
  // negotiated error naming the ranks.  Always FP32 for non-fp32
  // tensors and non-allreduce ops.
  WireDtype wire_dtype = WireDtype::FP32;
  // Set when wire_dtype was resolved from the GLOBAL knob
  // (HOROVOD_WIRE_DTYPE / a live TUNE) rather than a per-tensor
  // override.  Knob-derived wires are ADVISORY: enqueue-time sampling
  // races TUNE application across ranks (one rank's enqueue lands a
  // cycle before a peer applied the same TUNE), so the coordinator
  // COMMITS the first non-probe request's value instead of erroring —
  // every rank executes the response's committed wire anyway, and the
  // next step's signatures converge.  Only explicit overrides keep the
  // strict mismatch error.
  bool wire_default = false;
  // Scheduling PRIORITY for this tensor (0 = most urgent, the default).
  // Frontends stamp it from registration order (first-registered ≈ front
  // layer ≈ needed first by the NEXT step's forward), so with
  // HOROVOD_PRIORITY_BANDS > 0 the coordinator can order each cycle's
  // responses by (priority, name) instead of arrival order.  Validated
  // cross-rank like dtype/wire (probes exempt).  On the wire it travels
  // in a trailing tagged section of the RequestList carrying only the
  // NONZERO entries — an all-default frame is byte-identical to the
  // pre-priority protocol.
  int32_t priority = 0;
  std::vector<int64_t> shape;
  // Alltoall only: this rank's per-destination dim-0 row counts (size_
  // entries summing to shape[0]).  EMPTY means the legacy equal-split
  // contract (shape[0] divisible by world size).  Validated cross-rank
  // like the dim-0 allgather's geometry; the committed size×size split
  // matrix rides Response::tensor_sizes row-major.
  std::vector<int64_t> splits;
};

// Fleet telemetry (HOROVOD_TELEMETRY_CYCLES): every N negotiation cycles
// a rank piggybacks one TelemEntry of COUNTER DELTAS (since its previous
// send) on its RequestList, so rank 0 can maintain a fleet-wide counter
// table without a second wire protocol.  The deltas vector follows the
// fixed kTelemCounter order (engine.h); deltas-not-absolutes make the
// aggregation exact under hierarchical coordination, where a host
// leader SUMS its members' entries into one per-host entry (nranks
// grows, rank becomes the leader's) so rank 0 still receives O(hosts)
// telemetry bytes per telemetry cycle.  step/quorum percentiles are
// GAUGES (max-merged), with `slow_rank` attributing the worst step-time
// p99 inside a merged entry.
struct TelemEntry {
  int32_t rank = 0;        // reporting rank (host leader after a merge)
  int32_t nranks = 1;      // ranks aggregated into this entry
  int32_t host = 0;        // committed host-group id
  int64_t step_p50 = 0;    // step_time_ns_p50 gauge
  int64_t step_p99 = 0;    // step_time_ns_p99 gauge
  int32_t slow_rank = -1;  // rank with the largest step_p99 in this entry
  int64_t slow_p99 = 0;
  std::vector<int64_t> deltas;  // kTelemCounter order
};

struct RequestList {
  // Membership epoch this frame belongs to (elastic in-place resize).
  // Every control message is stamped with the sender's committed epoch;
  // a receiver on epoch E structurally rejects frames stamped != E, so a
  // delayed message from a dead incarnation of the world can never poison
  // the resized world's negotiation state (or replay a stale cache slot —
  // the PR 2 response cache is thereby keyed per-epoch).
  int64_t epoch = 0;
  std::vector<Request> requests;
  bool shutdown = false;    // shutdown piggybacks on the control stream
  // Hierarchical coordination: a sub-coordinator (per-host group leader)
  // that loses one of its local members cannot broadcast an abort itself
  // — it reports the culprit here so rank 0's abort verdict names the
  // rank that actually died, not the leader that noticed.  -1 = healthy.
  int32_t fail_rank = -1;
  std::string fail_message;
  // Response-cache control (upstream Horovod 0.21's bitvector idea): a
  // tensor whose (name, type, dtype, shape, root, op) was negotiated
  // before is reported as a single bit — the coordinator-assigned cache
  // slot id — instead of a full serialized Request.  On the wire the
  // hits travel bit-packed (slot ids are dense, bounded by
  // HOROVOD_CACHE_CAPACITY), so a steady-state step is a few bytes.
  std::vector<uint32_t> cache_hits;    // slot ids this rank is ready on
  // Slots this rank invalidated (same name re-enqueued with a different
  // signature); the full replacement Request rides in `requests` in the
  // same frame.
  std::vector<uint32_t> cache_evicts;
  // Piggybacked fleet telemetry (see TelemEntry).  The wire section is
  // appended ONLY when non-empty, and the parser reads it only when
  // bytes remain after the PR 12 fields — so HOROVOD_TELEMETRY_CYCLES=0
  // frames are BYTE-IDENTICAL to the pre-telemetry protocol, and an
  // idle telemetry cycle costs nothing at all (no flag byte: absence is
  // the flag).  Trailing sections are TAGGED (one u8 each: 1 = telem,
  // 2 = request priorities) so independent optional piggybacks compose
  // without spending bytes on the common all-absent frame.
  std::vector<TelemEntry> telem;
};

struct Response {
  ResponseType type = ResponseType::ALLREDUCE;
  // >1 names ⇒ fused batch executed as one collective.
  std::vector<std::string> tensor_names;
  std::string error_message;
  // Allgather: per-rank dim-0 sizes (negotiated dynamic shape).
  std::vector<int64_t> tensor_sizes;
  int32_t root_rank = -1;
  ReduceOp red_op = ReduceOp::SUM;
  // Committed wire format for this (possibly fused) allreduce response:
  // every rank validated-ly requested it, so the data plane quantizes/
  // dequantizes identically on all of them.  FP32 everywhere else.
  WireDtype wire_dtype = WireDtype::FP32;
  // Parallel to tensor_names: the cache slot the coordinator assigned to
  // each tensor (-1 = uncached).  Every rank inserts (name → slot,
  // slot → single-tensor response) into its local cache replica on
  // receipt, so later steps negotiate via RequestList::cache_hits.
  std::vector<int32_t> cache_slots;
  // Backup-worker PARTIAL commit (HOROVOD_BACKUP_WORKERS=k): the
  // committed participant rank set when the coordinator fired this SUM
  // allreduce at size-k voter readiness instead of waiting for the full
  // world.  Empty = full commit, the default contract (k=0 frames carry
  // one flag byte and nothing else).  Every rank executes the SAME ring
  // over the SAME response — a rank outside the set contributes a
  // zeroed buffer (zero is the SUM identity) so the wire pattern always
  // spans the whole world; partial_elems/partial_dtype carry the
  // payload geometry a skipped rank (which may hold no tensor entry at
  // all) needs to size that buffer.  Partial responses are never fused
  // and never assigned cache slots.
  std::vector<uint32_t> participants;
  int64_t partial_elems = 0;
  uint8_t partial_dtype = 0;
  // Committed scheduling priority of this (possibly fused) response.
  // NONZERO values ride the ResponseList's trailing tagged section
  // (tag 3) so every rank — including one that joined the negotiation
  // via a layout probe, whose own stamp was 0 — dispatches in the same
  // committed order; absence on the wire means "committed 0", keeping
  // the default frame byte-identical to the legacy protocol.  -1 = not
  // resolved yet (non-executable responses stay -1).
  int32_t priority = -1;
};

struct ResponseList {
  // Membership epoch (see RequestList::epoch).  Workers drop response
  // frames — including abort verdicts — stamped with a different epoch.
  int64_t epoch = 0;
  std::vector<Response> responses;
  bool shutdown = false;
  // Fault-tolerance abort broadcast: when the coordinator loses a rank
  // (EOF, keepalive, or HOROVOD_FAULT_TIMEOUT_SEC exceeded) it ships this
  // instead of a normal cycle so every SURVIVING rank fails its in-flight
  // and queued collectives promptly with a message naming the culprit,
  // rather than each rank discovering the death via its own transport
  // timeout one collective at a time.
  bool abort = false;
  int32_t abort_rank = -1;      // the rank the coordinator lost
  std::string abort_message;
  // Slots every rank agreed on this cycle (all size_ hit bits seen):
  // each rank executes the response stored in its local cache replica —
  // the coordinator never re-runs ConstructResponse and ships only the
  // slot ids.  Ascending slot order = deterministic execution order.
  std::vector<uint32_t> cached_slots;
  // Slots invalidated this cycle; every rank drops them from its replica.
  // A rank with a pending hit bit on an evicted slot resubmits that
  // tensor as a full Request next cycle.  Applied BEFORE cache_slots
  // assignments from the same frame (a freed slot may be reassigned in
  // the very cycle it was evicted).
  std::vector<uint32_t> evict_slots;
  // Online-autotuner TUNE broadcast (piggybacks on the regular cycle
  // frame, like `abort`): when `tune` is set, every receiver applies the
  // carried knob values BEFORE executing this cycle's responses — i.e.
  // atomically between negotiation cycles (no response in flight; and a
  // completion-woken enqueue can never read a stale knob a peer already
  // flipped), so no collective ever runs under a mixed config across
  // ranks.  The frame inherits the epoch
  // stamp above, so a TUNE from a dead incarnation of the world is
  // structurally dropped (and counted in stale_epoch_msgs) like any
  // other stale control frame.  A value <= 0 means "leave that knob
  // unchanged"; `tune_commit` marks the search's final (committed)
  // config for the timeline and observability.
  bool tune = false;
  bool tune_commit = false;
  int64_t tune_trial_id = 0;
  int64_t tune_chunk_bytes = 0;
  int64_t tune_fusion_threshold = 0;
  int32_t tune_cycle_time_ms = 0;
  int32_t tune_wave_width = 0;
  // Size-based algorithm-selection crossover (HOROVOD_ALGO_THRESHOLD).
  // Unlike the knobs above, 0 is a REAL value (small path disabled), so
  // "leave unchanged" is < 0.
  int64_t tune_algo_threshold = -1;
  // Live-tunable default wire dtype (the 6th knob): 0 (fp32) is a real
  // value, so "leave unchanged" is < 0.  Applies to enqueues AFTER the
  // frame lands; in-flight negotiations keep their requested format, and
  // the signature change evicts affected cache slots naturally.
  int32_t tune_wire_dtype = -1;
  // Priority band width (HOROVOD_PRIORITY_BANDS, the 7th live-tunable
  // knob): 0 is a REAL value (bands off = legacy arrival ordering), so
  // "leave unchanged" is < 0.
  int64_t tune_priority_bands = -1;
  // Per-band fusion-threshold ladder (autotuner-learned bucket sizes):
  // entry b sets band b's fusion threshold; <= 0 leaves that band
  // unchanged; an EMPTY vector leaves the whole ladder unchanged.
  std::vector<int64_t> tune_fusion_ladder;
  // Cached slots of this cycle's `cached_slots` that fired as
  // backup-worker PARTIAL commits: slot → committed participant set
  // (the replayed replica response provides the payload geometry from
  // its signature).  Leaders also drop their held sub-table bits for
  // these slots — the skipped group's ready members just had their
  // entries finished "skipped" and will re-report fresh.
  struct PartialSlot {
    uint32_t slot = 0;
    std::vector<uint32_t> participants;
  };
  std::vector<PartialSlot> partial_slots;
};

// Flat byte-buffer serialization (host byte order; in-cluster only).
// Fixed-width u32/i32/i64 remain for rendezvous handshakes (magic tags,
// pre-negotiation fields); the per-cycle control frames use the varint
// encoders below so steady-state negotiation bytes scale with the VALUES
// on the wire (small slot ids, small counts, small dims), not with the
// widest field any frame might ever need.
class Writer {
 public:
  void u8(uint8_t v) { buf_.push_back(v); }
  void u32(uint32_t v) { append(&v, 4); }
  void i32(int32_t v) { append(&v, 4); }
  void i64(int64_t v) { append(&v, 8); }
  // LEB128 varint: 7 value bits per byte, high bit = continuation.
  void vu(uint64_t v) {
    while (v >= 0x80) {
      buf_.push_back(static_cast<uint8_t>(v) | 0x80);
      v >>= 7;
    }
    buf_.push_back(static_cast<uint8_t>(v));
  }
  // ZigZag-mapped signed varint: small magnitudes of either sign stay
  // one byte (epochs, root ranks incl. -1, tensor dims).
  void vi(int64_t v) {
    vu((static_cast<uint64_t>(v) << 1) ^
       static_cast<uint64_t>(v >> 63));
  }
  void str(const std::string& s) {
    vu(s.size());
    append(s.data(), s.size());
  }
  const std::vector<uint8_t>& bytes() const { return buf_; }

 private:
  void append(const void* p, size_t n) {
    const uint8_t* b = static_cast<const uint8_t*>(p);
    buf_.insert(buf_.end(), b, b + n);
  }
  std::vector<uint8_t> buf_;
};

class Reader {
 public:
  Reader(const uint8_t* data, size_t size) : p_(data), end_(data + size) {}
  uint8_t u8() { return *take(1); }
  uint32_t u32() { uint32_t v; memcpy(&v, take(4), 4); return v; }
  int32_t i32() { int32_t v; memcpy(&v, take(4), 4); return v; }
  int64_t i64() { int64_t v; memcpy(&v, take(8), 8); return v; }
  uint64_t vu() {
    uint64_t v = 0;
    for (int shift = 0; shift < 64; shift += 7) {
      uint8_t b = u8();
      v |= static_cast<uint64_t>(b & 0x7f) << shift;
      if ((b & 0x80) == 0) return v;
    }
    ok_ = false;  // > 10 continuation bytes: corrupt frame
    return 0;
  }
  int64_t vi() {
    uint64_t v = vu();
    return static_cast<int64_t>((v >> 1) ^ (~(v & 1) + 1));
  }
  std::string str() {
    uint64_t n = vu();
    // Compare against the REMAINING length, never via p_ + n: with an
    // untrusted varint n near 2^64 the pointer sum overflows (UB) and
    // the check silently passes — a corrupt frame must fail parse
    // cleanly, not wrap into a multi-exabyte string construction.
    if (n > static_cast<uint64_t>(end_ - p_)) {
      ok_ = false;
      return std::string();
    }
    const uint8_t* s = take(static_cast<size_t>(n));
    return std::string(reinterpret_cast<const char*>(s), n);
  }
  bool ok() const { return ok_; }
  // Bytes not yet consumed.  Trailing optional sections (the TELEM
  // piggyback) are gated on this instead of a flag byte, so a frame
  // without the section is byte-identical to the pre-section protocol.
  size_t remaining() const { return static_cast<size_t>(end_ - p_); }

 private:
  const uint8_t* take(size_t n) {
    if (n > static_cast<size_t>(end_ - p_)) {
      ok_ = false;
      static uint8_t zero[8] = {0};
      return zero;
    }
    const uint8_t* r = p_;
    p_ += n;
    return r;
  }
  const uint8_t* p_;
  const uint8_t* end_;
  bool ok_ = true;
};

// -- link self-healing handshake (data-plane reconnect) --
//
// When a data-channel socket fails mid-collective and HOROVOD_LINK_RETRIES
// allows healing, the edge's ORIGIN (the ring sender, who opened the
// original wiring connect) re-dials the receiver's data listener and sends
// a RESUME hello instead of the 4-int wiring handshake; the receiver
// answers with an ACK carrying its authoritative chunk-cascade cursor
// (stream seq, step, byte offset within the step) so the sender rewinds
// and the collective completes bit-identically.  Fixed-width frames on a
// raw socket (both ends are the same build on the same arch): 6 and 5
// int64s, distinguished from wiring hellos by the magic in word 0 —
// wiring hellos start with a rank in [0, 2^31), these start with a magic
// far outside any epoch-stamped rank/field value.
constexpr int64_t kLinkResumeMagic = 0x4c52534d31ll;  // "LRSM1"
constexpr int64_t kLinkAckMagic = 0x4c52414b31ll;     // "LRAK1"

struct LinkResume {
  int64_t magic = kLinkResumeMagic;
  int64_t origin = -1;   // reconnecting rank (the edge's ring sender)
  int64_t ring = -1;     // RingId (engine.h): GLOBAL or CROSS
  int64_t channel = -1;  // global channel id of the failed edge
  int64_t epoch = -1;    // stale-incarnation connects are dropped, as ever
  int64_t seq = -1;      // sender's per-(ring,channel) cascade stream seq
};

struct LinkResumeAck {
  int64_t magic = kLinkAckMagic;
  int64_t ok = 0;      // 1 = cursor follows; 0 = declined (stream moved on)
  int64_t seq = -1;    // receiver's current stream seq for the channel
  int64_t step = 0;    // receiver's authoritative cascade step cursor
  int64_t offset = 0;  // bytes of `step` already received
};

// Validation-only decode helpers (the structs are sent raw): false when
// the magic does not match — the caller treats the frame as garbage.
bool ValidLinkResume(const LinkResume& r);
bool ValidLinkResumeAck(const LinkResumeAck& a);

void SerializeRequestList(const RequestList& list, Writer* w);
bool ParseRequestList(Reader* r, RequestList* out);
// Exposed for the engine's telem_bytes_tx accounting (the per-entry wire
// cost without serializing the whole frame twice).
void SerializeTelemEntry(const TelemEntry& t, Writer* w);
void SerializeResponseList(const ResponseList& list, Writer* w);
bool ParseResponseList(Reader* r, ResponseList* out);

}  // namespace hvd
