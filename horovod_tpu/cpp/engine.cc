#include "engine.h"

#include <algorithm>
#include <chrono>
#include <cstdio>
#include <cstdlib>
#include <sstream>

namespace hvd {

// ---------------------------------------------------------------------------
// Reduction kernels
// ---------------------------------------------------------------------------

// IEEE half <-> float, scalar bit twiddling (no F16C dependency; the
// compiler auto-vectorizes the loops below well enough for a host-side
// control-plane data path).
static inline float HalfToFloat(uint16_t h) {
  uint32_t sign = (h & 0x8000u) << 16;
  uint32_t exp = (h >> 10) & 0x1f;
  uint32_t man = h & 0x3ffu;
  uint32_t f;
  if (exp == 0) {
    if (man == 0) {
      f = sign;
    } else {
      exp = 127 - 15 + 1;
      while ((man & 0x400u) == 0) {
        man <<= 1;
        exp--;
      }
      man &= 0x3ffu;
      f = sign | (exp << 23) | (man << 13);
    }
  } else if (exp == 0x1f) {
    f = sign | 0x7f800000u | (man << 13);
  } else {
    f = sign | ((exp - 15 + 127) << 23) | (man << 13);
  }
  float out;
  memcpy(&out, &f, 4);
  return out;
}

static inline uint16_t FloatToHalf(float v) {
  uint32_t f;
  memcpy(&f, &v, 4);
  uint32_t sign = (f >> 16) & 0x8000u;
  int32_t exp = static_cast<int32_t>((f >> 23) & 0xff) - 127 + 15;
  uint32_t man = f & 0x7fffffu;
  if (exp <= 0) {
    if (exp < -10) return static_cast<uint16_t>(sign);
    man |= 0x800000u;
    uint32_t shift = static_cast<uint32_t>(14 - exp);
    uint32_t half_man = man >> shift;
    uint32_t round = (man >> (shift - 1)) & 1u;
    return static_cast<uint16_t>(sign | (half_man + round));
  }
  if (exp >= 0x1f) return static_cast<uint16_t>(sign | 0x7c00u);
  uint32_t half = sign | (static_cast<uint32_t>(exp) << 10) | (man >> 13);
  if (man & 0x1000u) half += 1;  // round-to-nearest
  return static_cast<uint16_t>(half);
}

// bfloat16 is float32's top 16 bits — the TPU-native conversion is two
// shifts (with round-to-nearest-even on the way down).
static inline float BF16ToFloat(uint16_t h) {
  uint32_t f = static_cast<uint32_t>(h) << 16;
  float out;
  memcpy(&out, &f, 4);
  return out;
}

static inline uint16_t FloatToBF16(float v) {
  uint32_t f;
  memcpy(&f, &v, 4);
  uint32_t rounding = 0x7fffu + ((f >> 16) & 1u);
  return static_cast<uint16_t>((f + rounding) >> 16);
}

template <typename T>
static void SumLoop(void* dst, const void* src, int64_t n) {
  T* d = static_cast<T*>(dst);
  const T* s = static_cast<const T*>(src);
  for (int64_t i = 0; i < n; ++i) d[i] += s[i];
}

void ReduceSumInto(void* dst, const void* src, int64_t count, DataType dtype) {
  switch (dtype) {
    case DataType::FLOAT32: SumLoop<float>(dst, src, count); return;
    case DataType::FLOAT64: SumLoop<double>(dst, src, count); return;
    case DataType::INT32: SumLoop<int32_t>(dst, src, count); return;
    case DataType::INT64: SumLoop<int64_t>(dst, src, count); return;
    case DataType::UINT8: SumLoop<uint8_t>(dst, src, count); return;
    case DataType::INT8: SumLoop<int8_t>(dst, src, count); return;
    case DataType::UINT16: SumLoop<uint16_t>(dst, src, count); return;
    case DataType::INT16: SumLoop<int16_t>(dst, src, count); return;
    case DataType::FLOAT16: {
      uint16_t* d = static_cast<uint16_t*>(dst);
      const uint16_t* s = static_cast<const uint16_t*>(src);
      for (int64_t i = 0; i < count; ++i) {
        d[i] = FloatToHalf(HalfToFloat(d[i]) + HalfToFloat(s[i]));
      }
      return;
    }
    case DataType::BFLOAT16: {
      uint16_t* d = static_cast<uint16_t*>(dst);
      const uint16_t* s = static_cast<const uint16_t*>(src);
      for (int64_t i = 0; i < count; ++i) {
        d[i] = FloatToBF16(BF16ToFloat(d[i]) + BF16ToFloat(s[i]));
      }
      return;
    }
    case DataType::BOOL: {
      uint8_t* d = static_cast<uint8_t*>(dst);
      const uint8_t* s = static_cast<const uint8_t*>(src);
      for (int64_t i = 0; i < count; ++i) d[i] = d[i] || s[i];
      return;
    }
  }
}

// ---------------------------------------------------------------------------
// Engine lifecycle
// ---------------------------------------------------------------------------

Engine& Engine::Get() {
  static Engine* engine = new Engine();
  return *engine;
}

static int64_t EnvInt64(const char* name, int64_t dflt) {
  const char* v = std::getenv(name);
  if (v == nullptr || v[0] == '\0') return dflt;
  return std::strtoll(v, nullptr, 10);
}

int Engine::Init(int rank, int size, int local_rank, int local_size,
                 const std::string& coordinator_addr) {
  if (initialized_.load()) return 0;
  rank_ = rank;
  size_ = size;
  local_rank_ = local_rank;
  local_size_ = local_size;
  shut_down_.store(false);
  shutdown_requested_.store(false);

  // Knobs (reference operations.cc:1556-1618).
  cycle_time_ms_ = static_cast<int>(EnvInt64("HOROVOD_CYCLE_TIME", 5));
  if (cycle_time_ms_ < 1) cycle_time_ms_ = 1;
  fusion_threshold_ = EnvInt64("HOROVOD_FUSION_THRESHOLD", 64 * 1024 * 1024);
  stall_check_disabled_ = EnvInt64("HOROVOD_STALL_CHECK_DISABLE", 0) != 0;
  stall_warning_sec_ =
      static_cast<int>(EnvInt64("HOROVOD_STALL_WARNING_SEC", 60));
  const char* timeline_path = std::getenv("HOROVOD_TIMELINE");
  if (timeline_path != nullptr && timeline_path[0] != '\0' && rank_ == 0) {
    timeline_.Initialize(timeline_path);
  }

  if (size_ > 1) {
    std::string host = "127.0.0.1";
    int port = 0;
    auto colon = coordinator_addr.rfind(':');
    if (colon != std::string::npos) {
      host = coordinator_addr.substr(0, colon);
      port = std::atoi(coordinator_addr.c_str() + colon + 1);
    }
    if (port == 0) {
      last_error_ = "coordinator address host:port required for size > 1";
      return 1;
    }
    std::string err;
    const char* my_host_env = std::getenv("HOROVOD_HOST");
    std::string my_host = my_host_env ? my_host_env : "127.0.0.1";

    // Every rank opens an ephemeral data listener for ring neighbors.
    int data_port = 0;
    data_listener_ = Listen("0.0.0.0", 0, 4, &data_port, &err);
    if (!data_listener_.valid()) {
      last_error_ = "data listener: " + err;
      return 1;
    }

    // Rendezvous: workers report (rank, host, data_port) to the
    // coordinator, which broadcasts the full peer table — the moral
    // equivalent of MPI_Init's wire-up or NCCL's ncclUniqueId broadcast
    // (reference operations.cc:894-931).
    std::vector<std::string> peer_hosts(size_);
    std::vector<int> peer_ports(size_, 0);
    if (rank_ == 0) {
      control_listener_ = Listen(host, port, size_ + 8, nullptr, &err);
      if (!control_listener_.valid()) {
        last_error_ = "coordinator listen on " + coordinator_addr + ": " + err;
        return 1;
      }
      peer_hosts[0] = my_host;
      peer_ports[0] = data_port;
      worker_conns_.clear();
      worker_conns_.resize(size_);
      for (int i = 1; i < size_; ++i) {
        Socket conn = Accept(control_listener_, &err);
        if (!conn.valid()) {
          last_error_ = "accept: " + err;
          return 1;
        }
        std::vector<uint8_t> frame;
        if (!conn.RecvFrame(&frame)) {
          last_error_ = "rendezvous recv failed";
          return 1;
        }
        Reader r(frame.data(), frame.size());
        int32_t peer_rank = r.i32();
        std::string peer_host = r.str();
        int32_t peer_port = r.i32();
        if (!r.ok() || peer_rank < 1 || peer_rank >= size_) {
          last_error_ = "bad rendezvous frame";
          return 1;
        }
        peer_hosts[peer_rank] = peer_host;
        peer_ports[peer_rank] = peer_port;
        worker_conns_[peer_rank] = std::move(conn);
      }
      Writer w;
      for (int i = 0; i < size_; ++i) {
        w.str(peer_hosts[i]);
        w.i32(peer_ports[i]);
      }
      for (int i = 1; i < size_; ++i) {
        if (!worker_conns_[i].SendFrame(w.bytes())) {
          last_error_ = "rendezvous bcast failed";
          return 1;
        }
      }
    } else {
      coordinator_conn_ = ConnectRetry(host, port, 60000, &err);
      if (!coordinator_conn_.valid()) {
        last_error_ = err;
        return 1;
      }
      Writer w;
      w.i32(rank_);
      w.str(my_host);
      w.i32(data_port);
      if (!coordinator_conn_.SendFrame(w.bytes())) {
        last_error_ = "rendezvous send failed";
        return 1;
      }
      std::vector<uint8_t> frame;
      if (!coordinator_conn_.RecvFrame(&frame)) {
        last_error_ = "rendezvous table recv failed";
        return 1;
      }
      Reader r(frame.data(), frame.size());
      for (int i = 0; i < size_; ++i) {
        peer_hosts[i] = r.str();
        peer_ports[i] = r.i32();
      }
      if (!r.ok()) {
        last_error_ = "bad rendezvous table";
        return 1;
      }
    }

    // Ring wiring: connect to (rank+1) % size, accept from (rank-1) % size.
    // Connect cannot deadlock: every listener already exists, so the
    // connect completes from the backlog even before the peer accepts.
    int next = (rank_ + 1) % size_;
    ring_next_ = ConnectRetry(peer_hosts[next], peer_ports[next], 60000, &err);
    if (!ring_next_.valid()) {
      last_error_ = "ring connect: " + err;
      return 1;
    }
    int32_t my_rank32 = rank_;
    if (!ring_next_.SendAll(&my_rank32, 4)) {
      last_error_ = "ring handshake send failed";
      return 1;
    }
    ring_prev_ = Accept(data_listener_, &err);
    if (!ring_prev_.valid()) {
      last_error_ = "ring accept: " + err;
      return 1;
    }
    int32_t prev_rank32 = -1;
    if (!ring_prev_.RecvAll(&prev_rank32, 4) ||
        prev_rank32 != (rank_ - 1 + size_) % size_) {
      last_error_ = "ring handshake mismatch";
      return 1;
    }
  }

  last_stall_check_ = std::chrono::steady_clock::now();
  initialized_.store(true);
  background_ = std::thread(&Engine::BackgroundLoop, this);
  return 0;
}

void Engine::Shutdown() {
  if (!initialized_.load() || shut_down_.load()) return;
  shutdown_requested_.store(true);
  if (background_.joinable()) background_.join();
  initialized_.store(false);
}

// ---------------------------------------------------------------------------
// Background negotiation loop
// ---------------------------------------------------------------------------

void Engine::BackgroundLoop() {
  while (RunLoopOnce()) {
  }
  // Fail anything still in flight (reference SHUT_DOWN_ERROR,
  // operations.cc:1647-1662).
  std::vector<TensorTableEntry> leftovers;
  {
    std::lock_guard<std::mutex> lk(mu_);
    for (auto& kv : tensor_table_) leftovers.push_back(std::move(kv.second));
    tensor_table_.clear();
    message_queue_.clear();
  }
  for (auto& e : leftovers) {
    FinishEntry(e, Status::Aborted(
        "Horovod has been shut down. This was caused by an exception on one "
        "of the ranks or an attempt to enqueue after shutdown."));
  }
  shut_down_.store(true);
}

bool Engine::RunLoopOnce() {
  std::this_thread::sleep_for(std::chrono::milliseconds(cycle_time_ms_));

  RequestList my_list;
  {
    std::lock_guard<std::mutex> lk(mu_);
    while (!message_queue_.empty()) {
      my_list.requests.push_back(std::move(message_queue_.front()));
      message_queue_.pop_front();
    }
  }
  my_list.shutdown = shutdown_requested_.load();

  if (size_ == 1) {
    // Single process: every tensor is instantly "globally ready".
    for (auto& q : my_list.requests) {
      timeline_.NegotiateStart(q.tensor_name);
      timeline_.NegotiateRankReady(q.tensor_name, 0);
      std::lock_guard<std::mutex> lk(mu_);
      auto& info = message_table_[q.tensor_name];
      info.requests.assign(1, q);
      info.seen.assign(1, true);
      info.count = 1;
    }
    std::vector<Response> responses;
    for (auto& q : my_list.requests) {
      timeline_.NegotiateEnd(q.tensor_name);
      responses.push_back(BuildResponse(q.tensor_name));
    }
    FuseResponses(responses);
    for (auto& resp : responses) PerformResponse(resp);
    return !my_list.shutdown;
  }

  if (rank_ == 0) {
    std::vector<RequestList> lists(size_);
    lists[0] = std::move(my_list);
    for (int r = 1; r < size_; ++r) {
      std::vector<uint8_t> frame;
      if (!worker_conns_[r].RecvFrame(&frame)) {
        std::fprintf(stderr,
                     "horovod_tpu coordinator: lost connection to rank %d\n",
                     r);
        return false;
      }
      Reader reader(frame.data(), frame.size());
      if (!ParseRequestList(&reader, &lists[r])) {
        std::fprintf(stderr, "horovod_tpu coordinator: bad frame from %d\n",
                     r);
        return false;
      }
    }
    ResponseList response_list = CoordinatorStep(lists);
    Writer w;
    SerializeResponseList(response_list, &w);
    for (int r = 1; r < size_; ++r) {
      if (!worker_conns_[r].SendFrame(w.bytes())) {
        std::fprintf(stderr,
                     "horovod_tpu coordinator: send to rank %d failed\n", r);
        return false;
      }
    }
    for (auto& resp : response_list.responses) PerformResponse(resp);
    if (!stall_check_disabled_) CheckForStalledTensors();
    return !response_list.shutdown;
  }

  // Worker: ship requests up, execute the agreed response list.
  Writer w;
  SerializeRequestList(my_list, &w);
  if (!coordinator_conn_.SendFrame(w.bytes())) {
    std::fprintf(stderr, "horovod_tpu rank %d: coordinator send failed\n",
                 rank_);
    return false;
  }
  std::vector<uint8_t> frame;
  if (!coordinator_conn_.RecvFrame(&frame)) {
    std::fprintf(stderr, "horovod_tpu rank %d: coordinator recv failed\n",
                 rank_);
    return false;
  }
  Reader reader(frame.data(), frame.size());
  ResponseList response_list;
  if (!ParseResponseList(&reader, &response_list)) {
    std::fprintf(stderr, "horovod_tpu rank %d: bad response frame\n", rank_);
    return false;
  }
  for (auto& resp : response_list.responses) PerformResponse(resp);
  return !response_list.shutdown;
}

// Readiness counting + response construction + fusion, on the coordinator.
// Reference: IncrementTensorCount (operations.cc:282-307) +
// ConstructMPIResponse (315-517) + fusion (1815-1842).
ResponseList Engine::CoordinatorStep(std::vector<RequestList>& lists) {
  ResponseList out;
  std::vector<std::string> became_ready;
  for (int r = 0; r < size_; ++r) {
    if (lists[r].shutdown) out.shutdown = true;
    for (auto& q : lists[r].requests) {
      auto it = message_table_.find(q.tensor_name);
      if (it == message_table_.end()) {
        timeline_.NegotiateStart(q.tensor_name);
        PendingInfo info;
        info.requests.resize(size_);
        info.seen.assign(size_, false);
        info.first_seen = std::chrono::steady_clock::now();
        it = message_table_.emplace(q.tensor_name, std::move(info)).first;
      }
      PendingInfo& info = it->second;
      if (!info.seen[r]) {
        info.seen[r] = true;
        info.requests[r] = q;
        info.count++;
        timeline_.NegotiateRankReady(q.tensor_name, r);
      }
      if (info.count == size_) {
        became_ready.push_back(q.tensor_name);
      }
    }
  }
  for (auto& name : became_ready) {
    timeline_.NegotiateEnd(name);
    out.responses.push_back(BuildResponse(name));
  }
  FuseResponses(out.responses);
  return out;
}

// Cross-rank validation: dtype / op / shape / root consistency.  Mismatch
// yields an ERROR response delivered to every rank instead of undefined
// collective behavior — the reference's most important failure-containment
// feature (operations.cc:315-517).
Response Engine::BuildResponse(const std::string& name) {
  PendingInfo info;
  {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = message_table_.find(name);
    info = std::move(it->second);
    message_table_.erase(it);
  }
  const Request& first = info.requests[0];
  Response resp;
  resp.tensor_names.push_back(name);
  std::ostringstream err;

  for (int r = 1; r < size_; ++r) {
    const Request& q = info.requests[r];
    if (q.type != first.type) {
      err << "Mismatched collective operations: rank 0 requested "
          << RequestTypeName(first.type) << " but rank " << r << " requested "
          << RequestTypeName(q.type) << " for tensor " << name << ".";
      resp.type = ResponseType::ERROR;
      resp.error_message = err.str();
      return resp;
    }
    if (q.dtype != first.dtype) {
      err << "Mismatched data types: rank 0 has " << DataTypeName(first.dtype)
          << " but rank " << r << " has " << DataTypeName(q.dtype)
          << " for tensor " << name << ".";
      resp.type = ResponseType::ERROR;
      resp.error_message = err.str();
      return resp;
    }
  }

  if (first.type == RequestType::ALLREDUCE ||
      first.type == RequestType::BROADCAST) {
    for (int r = 1; r < size_; ++r) {
      if (info.requests[r].shape != first.shape) {
        TensorShape s0, sr;
        for (auto d : first.shape) s0.AddDim(d);
        for (auto d : info.requests[r].shape) sr.AddDim(d);
        err << "Mismatched " << RequestTypeName(first.type)
            << " tensor shapes: rank 0 has shape " << s0.DebugString()
            << " but rank " << r << " has shape " << sr.DebugString()
            << " for tensor " << name << ".";
        resp.type = ResponseType::ERROR;
        resp.error_message = err.str();
        return resp;
      }
    }
  }
  if (first.type == RequestType::BROADCAST) {
    for (int r = 1; r < size_; ++r) {
      if (info.requests[r].root_rank != first.root_rank) {
        err << "Mismatched broadcast root ranks: rank 0 has root "
            << first.root_rank << " but rank " << r << " has root "
            << info.requests[r].root_rank << " for tensor " << name << ".";
        resp.type = ResponseType::ERROR;
        resp.error_message = err.str();
        return resp;
      }
    }
    resp.type = ResponseType::BROADCAST;
    resp.root_rank = first.root_rank;
    return resp;
  }
  if (first.type == RequestType::ALLGATHER) {
    // dim0 may differ per rank (the negotiated dynamic shape); the rest
    // must match.  tensor_sizes carries every rank's dim0.
    for (int r = 1; r < size_; ++r) {
      const auto& s = info.requests[r].shape;
      bool ok = s.size() == first.shape.size() && !s.empty();
      for (size_t d = 1; ok && d < s.size(); ++d) {
        ok = s[d] == first.shape[d];
      }
      if (first.shape.empty() || !ok) {
        err << "Mismatched allgather tensor shapes: all dimensions except "
               "the first must match across ranks for tensor "
            << name << ".";
        resp.type = ResponseType::ERROR;
        resp.error_message = err.str();
        return resp;
      }
    }
    resp.type = ResponseType::ALLGATHER;
    for (int r = 0; r < size_; ++r) {
      resp.tensor_sizes.push_back(info.requests[r].shape[0]);
    }
    return resp;
  }
  resp.type = ResponseType::ALLREDUCE;
  return resp;
}

// Consecutive same-dtype allreduces merge into one response executed as a
// single ring collective over the fusion buffer.
void Engine::FuseResponses(std::vector<Response>& responses) {
  if (fusion_threshold_ <= 0) return;
  auto entry_bytes = [this](const std::string& name) -> int64_t {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = tensor_table_.find(name);
    if (it == tensor_table_.end()) return 0;
    return it->second.shape.num_elements() *
           static_cast<int64_t>(DataTypeSize(it->second.dtype));
  };
  auto entry_dtype = [this](const std::string& name) -> DataType {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = tensor_table_.find(name);
    if (it == tensor_table_.end()) return DataType::FLOAT32;
    return it->second.dtype;
  };
  std::vector<Response> fused;
  for (auto& resp : responses) {
    if (resp.type == ResponseType::ALLREDUCE && !fused.empty() &&
        fused.back().type == ResponseType::ALLREDUCE &&
        entry_dtype(fused.back().tensor_names[0]) ==
            entry_dtype(resp.tensor_names[0])) {
      int64_t total = 0;
      for (auto& n : fused.back().tensor_names) total += entry_bytes(n);
      if (total + entry_bytes(resp.tensor_names[0]) <= fusion_threshold_) {
        fused.back().tensor_names.push_back(resp.tensor_names[0]);
        continue;
      }
    }
    fused.push_back(std::move(resp));
  }
  responses = std::move(fused);
}

// ---------------------------------------------------------------------------
// Execution (the host data plane)
// ---------------------------------------------------------------------------

void Engine::PerformResponse(const Response& response) {
  std::vector<TensorTableEntry> entries;
  {
    std::lock_guard<std::mutex> lk(mu_);
    for (const auto& name : response.tensor_names) {
      auto it = tensor_table_.find(name);
      if (it != tensor_table_.end()) {
        entries.push_back(std::move(it->second));
        tensor_table_.erase(it);
      }
    }
  }
  if (response.type == ResponseType::ERROR) {
    for (auto& e : entries) {
      FinishEntry(e, Status::PreconditionError(response.error_message));
    }
    return;
  }
  if (entries.empty()) return;
  switch (response.type) {
    case ResponseType::ALLREDUCE:
      ExecAllreduce(response, entries);
      break;
    case ResponseType::ALLGATHER:
      ExecAllgather(response, entries);
      break;
    case ResponseType::BROADCAST:
      ExecBroadcast(response, entries);
      break;
    default:
      break;
  }
}

// Bandwidth-optimal ring allreduce: reduce-scatter + allgather over the
// neighbor sockets.  Send and recv run concurrently (sender thread) so the
// ring never deadlocks on socket buffers.
static bool RingAllreduce(void* data, int64_t count, DataType dtype,
                          int rank, int size, Socket& next, Socket& prev,
                          std::string* err) {
  const size_t esize = DataTypeSize(dtype);
  uint8_t* base = static_cast<uint8_t*>(data);
  std::vector<int64_t> seg_count(size), seg_off(size);
  int64_t off = 0;
  for (int s = 0; s < size; ++s) {
    seg_count[s] = count / size + (s < count % size ? 1 : 0);
    seg_off[s] = off;
    off += seg_count[s];
  }
  std::vector<uint8_t> tmp(static_cast<size_t>(seg_count[0]) * esize);

  // Reduce-scatter: after step t, rank r owns the full sum of segment
  // (r - t - 1) mod size's partials seen so far.
  for (int step = 0; step < size - 1; ++step) {
    int send_seg = (rank - step + size) % size;
    int recv_seg = (rank - step - 1 + size) % size;
    bool send_ok = true;
    std::thread sender([&] {
      send_ok = next.SendAll(base + seg_off[send_seg] * esize,
                             static_cast<size_t>(seg_count[send_seg]) * esize);
    });
    bool recv_ok = prev.RecvAll(
        tmp.data(), static_cast<size_t>(seg_count[recv_seg]) * esize);
    sender.join();
    if (!send_ok || !recv_ok) {
      *err = "ring reduce-scatter transport failure";
      return false;
    }
    ReduceSumInto(base + seg_off[recv_seg] * esize, tmp.data(),
                  seg_count[recv_seg], dtype);
  }
  // Allgather: circulate the fully-reduced segments.
  for (int step = 0; step < size - 1; ++step) {
    int send_seg = (rank - step + 1 + size) % size;
    int recv_seg = (rank - step + size) % size;
    bool send_ok = true;
    std::thread sender([&] {
      send_ok = next.SendAll(base + seg_off[send_seg] * esize,
                             static_cast<size_t>(seg_count[send_seg]) * esize);
    });
    bool recv_ok = prev.RecvAll(
        base + seg_off[recv_seg] * esize,
        static_cast<size_t>(seg_count[recv_seg]) * esize);
    sender.join();
    if (!send_ok || !recv_ok) {
      *err = "ring allgather transport failure";
      return false;
    }
  }
  return true;
}

void Engine::ExecAllreduce(const Response& response,
                           std::vector<TensorTableEntry>& entries) {
  const std::string& tname = entries[0].name;
  for (auto& e : entries) timeline_.Start(e.name);
  DataType dtype = entries[0].dtype;
  int64_t total = 0;
  for (auto& e : entries) total += e.shape.num_elements();

  if (size_ > 1) {
    void* buf = entries[0].data;
    const size_t esize = DataTypeSize(dtype);
    if (entries.size() > 1) {
      timeline_.ActivityStart(tname, "MEMCPY_IN_FUSION_BUFFER");
      if (fusion_buffer_.size() < static_cast<size_t>(total) * esize) {
        fusion_buffer_.resize(static_cast<size_t>(total) * esize);
      }
      int64_t off = 0;
      for (auto& e : entries) {
        size_t n = static_cast<size_t>(e.shape.num_elements()) * esize;
        memcpy(fusion_buffer_.data() + off, e.data, n);
        off += n;
      }
      buf = fusion_buffer_.data();
      timeline_.ActivityEnd(tname);
    }
    timeline_.ActivityStart(tname, "RING_ALLREDUCE");
    std::string err;
    if (!RingAllreduce(buf, total, dtype, rank_, size_, ring_next_,
                       ring_prev_, &err)) {
      timeline_.ActivityEnd(tname);
      for (auto& e : entries) FinishEntry(e, Status::Aborted(err));
      return;
    }
    timeline_.ActivityEnd(tname);
    if (entries.size() > 1) {
      timeline_.ActivityStart(tname, "MEMCPY_OUT_FUSION_BUFFER");
      int64_t off = 0;
      for (auto& e : entries) {
        size_t n = static_cast<size_t>(e.shape.num_elements()) * esize;
        memcpy(e.data, fusion_buffer_.data() + off, n);
        off += n;
      }
      timeline_.ActivityEnd(tname);
    }
  }
  for (auto& e : entries) {
    timeline_.End(e.name, e.dtype, e.shape.DebugString());
    FinishEntry(e, Status::OK());
  }
}

void Engine::ExecAllgather(const Response& response,
                           std::vector<TensorTableEntry>& entries) {
  // Allgather is never fused (matches the reference); one entry.
  TensorTableEntry& e = entries[0];
  timeline_.Start(e.name);
  const size_t esize = DataTypeSize(e.dtype);
  int64_t slice = 1;
  for (int d = 1; d < e.shape.ndim(); ++d) slice *= e.shape.dim(d);

  int64_t total_dim0 = 0;
  for (auto v : response.tensor_sizes) total_dim0 += v;

  auto hs = GetHandle(e.handle);
  if (hs == nullptr) return;
  hs->result.resize(static_cast<size_t>(total_dim0 * slice) * esize);
  hs->result_shape.clear();
  hs->result_shape.push_back(total_dim0);
  for (int d = 1; d < e.shape.ndim(); ++d) {
    hs->result_shape.push_back(e.shape.dim(d));
  }

  std::vector<int64_t> block_bytes(size_), block_off(size_);
  int64_t off = 0;
  for (int r = 0; r < size_; ++r) {
    block_bytes[r] = response.tensor_sizes[r] * slice *
                     static_cast<int64_t>(esize);
    block_off[r] = off;
    off += block_bytes[r];
  }
  memcpy(hs->result.data() + block_off[rank_], e.data,
         static_cast<size_t>(block_bytes[rank_]));

  if (size_ > 1) {
    timeline_.ActivityStart(e.name, "RING_ALLGATHER");
    // Circulate blocks around the ring; after size-1 steps everyone has all.
    bool failed = false;
    for (int step = 0; step < size_ - 1 && !failed; ++step) {
      int send_block = (rank_ - step + size_) % size_;
      int recv_block = (rank_ - step - 1 + size_) % size_;
      bool send_ok = true;
      std::thread sender([&] {
        send_ok = ring_next_.SendAll(
            hs->result.data() + block_off[send_block],
            static_cast<size_t>(block_bytes[send_block]));
      });
      bool recv_ok = ring_prev_.RecvAll(
          hs->result.data() + block_off[recv_block],
          static_cast<size_t>(block_bytes[recv_block]));
      sender.join();
      failed = !send_ok || !recv_ok;
    }
    timeline_.ActivityEnd(e.name);
    if (failed) {
      FinishEntry(e, Status::Aborted("ring allgather transport failure"));
      return;
    }
  }
  timeline_.End(e.name, e.dtype, e.shape.DebugString());
  FinishEntry(e, Status::OK());
}

void Engine::ExecBroadcast(const Response& response,
                           std::vector<TensorTableEntry>& entries) {
  TensorTableEntry& e = entries[0];
  timeline_.Start(e.name);
  if (size_ > 1) {
    timeline_.ActivityStart(e.name, "RING_BROADCAST");
    size_t nbytes = static_cast<size_t>(e.shape.num_elements()) *
                    DataTypeSize(e.dtype);
    int root = response.root_rank;
    bool ok = true;
    // Pipeline root → root+1 → ... → root-1 along the ring.
    if (rank_ == root) {
      if (size_ > 1) ok = ring_next_.SendAll(e.data, nbytes);
    } else {
      ok = ring_prev_.RecvAll(e.data, nbytes);
      int next = (rank_ + 1) % size_;
      if (ok && next != root) ok = ring_next_.SendAll(e.data, nbytes);
    }
    timeline_.ActivityEnd(e.name);
    if (!ok) {
      FinishEntry(e, Status::Aborted("ring broadcast transport failure"));
      return;
    }
  }
  timeline_.End(e.name, e.dtype, e.shape.DebugString());
  FinishEntry(e, Status::OK());
}

void Engine::FinishEntry(TensorTableEntry& e, const Status& s) {
  auto hs = GetHandle(e.handle);
  if (hs == nullptr) return;
  {
    std::lock_guard<std::mutex> lk(handle_mu_);
    hs->error = s.reason();
    hs->done.store(s.ok() ? 1 : -1);
  }
  handle_cv_.notify_all();
}

// Rank-0-only stall warnings naming the missing ranks (reference
// CheckForStalledTensors, operations.cc:1366-1412).
void Engine::CheckForStalledTensors() {
  auto now = std::chrono::steady_clock::now();
  if (now - last_stall_check_ < std::chrono::seconds(stall_warning_sec_)) {
    return;
  }
  last_stall_check_ = now;
  std::lock_guard<std::mutex> lk(mu_);
  bool preamble = false;
  for (auto& kv : message_table_) {
    auto age = std::chrono::duration_cast<std::chrono::seconds>(
                   now - kv.second.first_seen)
                   .count();
    if (age < stall_warning_sec_) continue;
    if (!preamble) {
      std::fprintf(
          stderr,
          "One or more tensors were submitted to be reduced, gathered or "
          "broadcasted by subset of ranks and are waiting for remainder of "
          "ranks for more than %d seconds. This may indicate that different "
          "ranks are trying to submit different tensors or that only subset "
          "of ranks is submitting tensors, which will cause deadlock.\n",
          stall_warning_sec_);
      std::fprintf(stderr, "Stalled ops:\n");
      preamble = true;
    }
    std::string missing;
    for (int r = 0; r < size_; ++r) {
      if (!kv.second.seen[r]) {
        if (!missing.empty()) missing += ", ";
        missing += std::to_string(r);
      }
    }
    std::fprintf(stderr, "%s [missing ranks: %s]\n", kv.first.c_str(),
                 missing.c_str());
  }
}

// ---------------------------------------------------------------------------
// Public enqueue / handle API
// ---------------------------------------------------------------------------

int64_t Engine::Enqueue(RequestType type, const std::string& name,
                        DataType dtype, const std::vector<int64_t>& shape,
                        void* data, int root_rank) {
  if (!initialized_.load() || shutdown_requested_.load() ||
      shut_down_.load()) {
    return -2;
  }
  int64_t handle = next_handle_.fetch_add(1);
  auto hs = std::make_shared<HandleState>();
  {
    std::lock_guard<std::mutex> lk(handle_mu_);
    handles_[handle] = hs;
  }
  TensorTableEntry e;
  e.name = name;
  e.type = type;
  e.dtype = dtype;
  for (auto d : shape) e.shape.AddDim(d);
  e.data = data;
  e.root_rank = root_rank;
  e.handle = handle;

  Request q;
  q.request_rank = rank_;
  q.type = type;
  q.dtype = dtype;
  q.tensor_name = name;
  q.root_rank = root_rank;
  q.shape = shape;

  {
    std::lock_guard<std::mutex> lk(mu_);
    if (tensor_table_.count(name) != 0) {
      std::lock_guard<std::mutex> hlk(handle_mu_);
      handles_.erase(handle);
      return -1;  // duplicate name in flight
    }
    tensor_table_.emplace(name, std::move(e));
    message_queue_.push_back(std::move(q));
  }
  return handle;
}

std::shared_ptr<HandleState> Engine::GetHandle(int64_t handle) {
  std::lock_guard<std::mutex> lk(handle_mu_);
  auto it = handles_.find(handle);
  return it == handles_.end() ? nullptr : it->second;
}

int Engine::Poll(int64_t handle) {
  auto hs = GetHandle(handle);
  if (hs == nullptr) return -1;
  return hs->done.load();
}

int Engine::Wait(int64_t handle) {
  auto hs = GetHandle(handle);
  if (hs == nullptr) return -1;
  std::unique_lock<std::mutex> lk(handle_mu_);
  handle_cv_.wait(lk, [&] { return hs->done.load() != 0; });
  return hs->done.load();
}

std::string Engine::ErrorMessage(int64_t handle) {
  auto hs = GetHandle(handle);
  if (hs == nullptr) return "unknown handle";
  std::lock_guard<std::mutex> lk(handle_mu_);
  return hs->error;
}

int64_t Engine::ResultNumDims(int64_t handle) {
  auto hs = GetHandle(handle);
  if (hs == nullptr) return -1;
  return static_cast<int64_t>(hs->result_shape.size());
}

int64_t Engine::ResultDim(int64_t handle, int i) {
  auto hs = GetHandle(handle);
  if (hs == nullptr || i < 0 ||
      i >= static_cast<int>(hs->result_shape.size())) {
    return -1;
  }
  return hs->result_shape[i];
}

int64_t Engine::ResultByteSize(int64_t handle) {
  auto hs = GetHandle(handle);
  if (hs == nullptr) return -1;
  return static_cast<int64_t>(hs->result.size());
}

int Engine::CopyResult(int64_t handle, void* dst, int64_t nbytes) {
  auto hs = GetHandle(handle);
  if (hs == nullptr || nbytes < static_cast<int64_t>(hs->result.size())) {
    return -1;
  }
  memcpy(dst, hs->result.data(), hs->result.size());
  return 0;
}

void Engine::ReleaseHandle(int64_t handle) {
  std::lock_guard<std::mutex> lk(handle_mu_);
  handles_.erase(handle);
}

}  // namespace hvd
